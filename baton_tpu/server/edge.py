"""Edge aggregator — the hierarchical tier between workers and root.

One :class:`EdgeAggregator` fronts a cohort of workers for a single
root manager experiment, collapsing the root's per-round work from
``O(C)`` to ``O(E)`` on both planes:

* **Downlink.** The edge fetches each round blob from the root ONCE
  (Range-resumable, digest-verified — the same pull contract the
  worker speaks) and serves its cohort from a local content-addressed
  :class:`~baton_tpu.server.blobs.BlobStore` with the root's exact
  Range/ETag semantics, so a worker cannot tell which tier it is
  talking to.
* **Uplink.** The edge runs its own
  :class:`~baton_tpu.server.ingest.IngestPipeline` to decode/validate
  cohort updates off-loop and folds them into a weighted
  :class:`~baton_tpu.ops.aggregation.StreamingMean` partial. When the
  cohort has reported (or ``flush_after_s`` expires) it ships ONE
  ``edge_partial`` update upstream — the partial mean, the summed
  sample weight, and the contributor set — which the root merges
  ``ShardedStreamingMean``-style (weighted sums are associative, so
  the tree fold equals the flat fold to fp32 reduction order).

Control plane: workers register/heartbeat THROUGH the edge. The
registration proxy rewrites each worker's callback URL to this edge's
``/relay/`` endpoint, so the root's notify and secure-protocol POSTs
route back through the edge hop (carrying ``traceparent`` — one round
stays one trace), while the credentials the worker holds are ROOT
credentials: a worker that loses its edge falls back to the root
directly without re-registering (see ``http_worker._edge_failed``).

Deliberate non-goals, all of which degrade to the flat topology
instead of failing:

* **Masked (secure-aggregation) uploads are refused with 409** — a
  partial fold of ring elements would break unmasking (the pairwise
  masks only cancel in the full cohort sum). The worker pins masked
  bodies to its direct root route; the 409 is a guard, not a path.
* **Compressed/quantized uploads and encoded broadcasts proxy
  through** — the edge folds dense template-shaped tensors only.
* **An unknown round proxies through** — the root is authoritative
  about liveness; the edge never turns its own staleness into a 410.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import logging
import random
import re
import time
from typing import Dict, Optional, Set, Tuple

import aiohttp
from aiohttp import web
import numpy as np

from baton_tpu.obs import alerts as obs_alerts
from baton_tpu.ops.aggregation import StreamingMean
from baton_tpu.server import wire
from baton_tpu.server.blobs import BlobStore
from baton_tpu.server.fleet import ClientLedger
from baton_tpu.server.ingest import IngestPipeline
from baton_tpu.server.utils import (
    BodyTooLarge,
    PeriodicTask,
    json_clean,
    random_key,
    read_body_capped,
    read_json_capped,
)
from baton_tpu.utils import tracing
from baton_tpu.utils.metrics import Metrics
from baton_tpu.utils.tracing import Tracer, trace_headers

MAX_BACKOFF = 30.0


@dataclasses.dataclass
class _WorkerRoute:
    """One proxied worker: its real callback URL (for root→worker
    relays) and its ROOT credentials' key (for authenticating the
    worker's own blob/update requests at this edge — the register
    proxy sees the key on its way back to the worker)."""

    url: str
    key: str


class _ChunkSession:
    """Uplink chunk reassembly state — same offset-committed contract
    as the manager's (manager is authoritative shape; see
    ``http_manager.handle_update_chunk``)."""

    __slots__ = ("client_id", "update_id", "total", "buf", "busy",
                 "content_type")

    def __init__(self, client_id: str, update_id: str, total: int) -> None:
        self.client_id = client_id
        self.update_id = update_id
        self.total = total
        self.buf = bytearray()
        self.busy = False
        self.content_type = wire.CONTENT_TYPE

    @property
    def offset(self) -> int:
        return len(self.buf)


class _EdgeRound:
    """Per-round fold state. One instance per observed ``round_start``
    envelope; retired (and its unshipped partial counted abandoned)
    when the next round's envelope arrives."""

    def __init__(
        self, round_name: str, n_epoch: int, digest: str, size: int,
        proxy_only: bool, secure: bool,
    ) -> None:
        self.round_name = round_name
        self.n_epoch = n_epoch
        self.digest = digest
        self.size = size
        # proxy_only: secure or encoded broadcasts — the edge cannot
        # derive a dense validation template, so uplink passes through
        self.proxy_only = proxy_only
        self.secure = secure
        self.acc = StreamingMean()
        self.template: Optional[dict] = None
        self.template_ready = asyncio.Event()
        # contributor bookkeeping shipped inside the partial's meta
        self.contributors: Dict[str, dict] = {}
        self.update_ids: Set[str] = set()
        self.notified: Set[str] = set()
        self.shipped = False
        self.shipping = False
        # accepted updates whose fold is still queued in the pipeline:
        # ship must drain these or the partial's mean would omit tensors
        # its contributor set credits
        self.pending_folds = 0
        self.ship_update_id = random_key(16)
        self.settle_task: Optional[asyncio.Future] = None
        self.deadline_task: Optional[asyncio.Future] = None
        # per-round phase wall times shipped upstream in the partial's
        # meta (the root folds them into the round's counter deltas)
        self.t0 = time.monotonic()
        self.fold_s = 0.0
        self.fetch_s = 0.0

    def cancel_tasks(self) -> None:
        for t in (self.settle_task, self.deadline_task):
            if t is not None and not t.done():
                t.cancel()


class EdgeAggregator:
    """HTTP edge tier for one experiment ``name``.

    Speaks the worker-facing manager protocol downward (register /
    heartbeat / round_blob / update / update_chunk / trace_spans) and
    the worker protocol upward (it registers at the root as a client
    of its own, with a callback that declines cohort membership).
    """

    _RANGE_RE = re.compile(r"bytes=(\d+)-(\d*)$")

    def __init__(
        self,
        app: web.Application,
        manager: str,
        name: str,
        port: int,
        edge_name: Optional[str] = None,
        edge_host: str = "127.0.0.1",
        heartbeat_time: float = 30.0,
        ship_settle_s: float = 0.25,
        flush_after_s: float = 20.0,
        ingest_workers: int = 2,
        ingest_queue_depth: int = 64,
        upload_chunk_bytes: Optional[int] = None,
        max_upload_bytes: Optional[int] = 1 << 30,
        metrics: Optional[Metrics] = None,
        clients_log_path: Optional[str] = None,
        health_window: int = 32,
        metrics_history_interval_s: float = 5.0,
        alert_rules: Optional[list] = None,
        alerts_log_path: Optional[str] = None,
        alerts_interval_s: float = 1.0,
        auto_start: bool = True,
    ) -> None:
        self.name = name
        self.port = port
        self.host = edge_host
        self.edge_name = edge_name or f"edge_{random_key(6)}"
        self.root_url = f"http://{manager}/{self.name}/"
        self.heartbeat_time = float(heartbeat_time)
        self.ship_settle_s = float(ship_settle_s)
        self.flush_after_s = float(flush_after_s)
        self.upload_chunk_bytes = upload_chunk_bytes
        self.max_upload_bytes = max_upload_bytes

        self.metrics = metrics if metrics is not None else Metrics()
        self.tracer = Tracer(service=f"edge:{self.edge_name}")
        # this tier's half of the fleet health plane: the edge ledgers
        # its own cohort (the workers it relays for), the root ledgers
        # everyone — same scoring, different vantage point
        self.fleet = ClientLedger(
            window=health_window, log_path=clients_log_path,
            metrics=self.metrics, node=f"edge:{self.edge_name}",
        )
        self.metrics_history_interval_s = float(metrics_history_interval_s)
        self._history_task: Optional[PeriodicTask] = None
        # alerting plane, edge vantage: the same declarative engine the
        # root runs, over this edge's own metric namespace (rules that
        # select rounds.* series simply skip here — the edge keeps no
        # rounds.jsonl tail). No forensics on edges: the deep-capture
        # evidence (profiler, round trace) lives at the root.
        self.alerts_interval_s = float(alerts_interval_s)
        self.alerts = obs_alerts.AlertEngine(
            alert_rules,
            log_path=alerts_log_path,
            metrics=self.metrics,
            node=f"edge:{self.edge_name}",
        )
        self._alerts_task: Optional[PeriodicTask] = None
        self._last_ship_s: Optional[float] = None
        self._pipe = IngestPipeline(
            workers=ingest_workers, queue_depth=ingest_queue_depth,
            fold_shards=1, metrics=self.metrics, tracer=self.tracer,
        )
        self.blob_cache = BlobStore()
        # expected byte sizes from the current envelope (full + deltas):
        # doubles as the cache-retention set at round roll
        self._blob_sizes: Dict[str, int] = {}
        self._blob_waits: Dict[str, asyncio.Future] = {}

        self._workers: Dict[str, _WorkerRoute] = {}
        self._round: Optional[_EdgeRound] = None
        self._chunks: Dict[Tuple[str, str], _ChunkSession] = {}

        # this edge's OWN root credentials (blob fetch, partial upload,
        # trace shipping) — lazily established, rotated on 401
        self.client_id: Optional[str] = None
        self.key: str = ""
        self._register_lock = asyncio.Lock()
        self._closed = False
        self._heartbeat_task: Optional[PeriodicTask] = None
        self.__session: Optional[aiohttp.ClientSession] = None

        r = app.router
        r.add_get(f"/{self.name}/register", self.handle_register)
        r.add_get(f"/{self.name}/heartbeat", self.handle_heartbeat)
        r.add_get(
            f"/{self.name}/round_blob/{{digest}}", self.handle_round_blob
        )
        r.add_post(f"/{self.name}/update", self.handle_update)
        r.add_put(
            f"/{self.name}/update_chunk/{{update_id}}",
            self.handle_update_chunk,
        )
        r.add_get(
            f"/{self.name}/update_chunk/{{update_id}}",
            self.handle_update_chunk_probe,
        )
        r.add_post(f"/{self.name}/trace_spans", self.handle_trace_spans)
        r.add_post(f"/{self.name}/relay/{{tail}}", self.handle_relay)
        r.add_post(f"/{self.name}/edge/{{tail}}", self.handle_edge_callback)
        r.add_get(f"/{self.name}/metrics", self.handle_metrics)
        r.add_get(
            f"/{self.name}/metrics/history", self.handle_metrics_history
        )
        r.add_get(f"/{self.name}/fleet/health", self.handle_fleet_health)
        r.add_get(f"/{self.name}/alerts", self.handle_alerts)
        if auto_start:
            app.on_startup.append(self._on_startup)
            app.on_cleanup.append(self._on_cleanup)

    # -- lifecycle -----------------------------------------------------
    async def _on_startup(self, app=None) -> None:
        asyncio.ensure_future(self._ensure_registered())
        self._heartbeat_task = PeriodicTask(
            self._heartbeat_tick, self.heartbeat_time
        ).start()
        if self.metrics_history_interval_s > 0:
            self._history_task = PeriodicTask(
                self._history_tick, self.metrics_history_interval_s
            ).start()
        if self.alerts.rules and self.alerts_interval_s > 0:
            self._alerts_task = PeriodicTask(
                self._alerts_tick, self.alerts_interval_s
            ).start()

    async def _history_tick(self) -> None:
        self.fleet.export_gauges(self.metrics)
        self.metrics.record_history()

    async def _alerts_tick(self) -> None:
        # advisory plane: a failed evaluation is counted, never raised
        try:
            self.fleet.export_gauges(self.metrics)
            view = obs_alerts.build_metric_view(self.metrics.snapshot())
            self.alerts.evaluate(view, history=self.metrics.history())
        except Exception:
            self.metrics.inc("alerts_eval_errors")
            logging.getLogger(__name__).exception(
                "%s: edge alert evaluation tick failed", self.edge_name
            )

    async def _on_cleanup(self, app=None) -> None:
        self._closed = True
        if self._heartbeat_task is not None:
            await self._heartbeat_task.stop()
        if self._history_task is not None:
            await self._history_task.stop()
        if self._alerts_task is not None:
            await self._alerts_task.stop()
        r = self._round
        if r is not None:
            r.cancel_tasks()
            if not r.shipped and r.contributors:
                self.metrics.inc("edge_partials_abandoned")
        self._pipe.shutdown()
        if self.__session is not None:
            await self.__session.close()

    @property
    def _session(self) -> aiohttp.ClientSession:
        if self.__session is None:
            self.__session = aiohttp.ClientSession()
        return self.__session

    def _creds(self) -> str:
        return f"client_id={self.client_id}&key={self.key}"

    def _invalidate_credentials(self, stale_id: Optional[str]) -> None:
        """Drop credentials observed to 401 — unless a handshake that
        completed during the observing await already replaced them (the
        401 then belonged to the OLD identity and the fresh credentials
        must survive). The compare and the write run loop-atomically,
        so this can never clobber an in-flight ``_register_with_root``
        commit the way a blind ``self.client_id = None`` could."""
        if stale_id is not None and self.client_id == stale_id:
            # guarded by the compare above, not by _register_lock
            self.client_id = None  # batonlint: allow[BTL004]

    async def _ensure_registered(self) -> None:
        if self.client_id is not None:
            return
        await self._register_with_root()

    async def _register_with_root(self) -> None:
        """Register this edge as a root client of its own. The callback
        points at ``/edge/`` — a stub that observes round envelopes and
        politely declines cohort membership with 409 (never 404, which
        would get these credentials dropped)."""
        if self._register_lock.locked():
            # collision guard: piggyback on the in-flight handshake
            async with self._register_lock:
                return
        async with self._register_lock:  # batonlint: allow[BTL002]
            payload = {
                "url": f"http://{self.host}:{self.port}/{self.name}/edge/"
            }
            backoff = 0.5
            while not self._closed:
                try:
                    async with self._session.get(
                        self.root_url + "register", json=payload
                    ) as resp:
                        data = await resp.json()
                        self.client_id = data["client_id"]
                        self.key = data["key"]
                        return
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        RuntimeError, TypeError, KeyError, ValueError):
                    # RuntimeError: session closed mid-shutdown
                    await asyncio.sleep(backoff * (0.5 + random.random() / 2))
                    backoff = min(backoff * 2, MAX_BACKOFF)

    async def _heartbeat_tick(self) -> None:
        """Keep this edge's own registry entry alive (the root TTL-culls
        silent clients, edge included). Single attempt per tick; a 401
        means the root restarted — rejoin with fresh credentials."""
        if self.client_id is None:
            await self._ensure_registered()
            return
        try:
            cid = self.client_id
            with self.metrics.timer("heartbeat_s"):
                async with self._session.get(
                    self.root_url + "heartbeat",
                    json={"client_id": cid, "key": self.key},
                ) as resp:
                    status = resp.status
            if status == 401:
                self._invalidate_credentials(cid)
                await self._ensure_registered()
        except (aiohttp.ClientError, asyncio.TimeoutError):
            pass  # next tick retries; workers fall back direct meanwhile

    # -- membership proxy ----------------------------------------------
    async def handle_register(self, request: web.Request) -> web.Response:
        """Register a worker at the ROOT, substituting this edge's relay
        endpoint as the callback so notify/secure traffic routes back
        through this hop. The response (root credentials) passes through
        untouched — the worker can always fall back to the root with
        the same identity."""
        try:
            data = await read_json_capped(request)
        except BodyTooLarge as exc:
            return web.json_response(
                {"err": "Body Too Large", "limit_bytes": exc.limit},
                status=413,
            )
        # the worker's REAL callback, derived exactly as the root
        # registry would have derived it had the worker gone direct
        worker_url = data.get("url") or (
            f"http://{request.remote}:{data.get('port')}/{self.name}/"
        )
        if not worker_url.endswith("/"):
            worker_url += "/"
        relay = f"http://{self.host}:{self.port}/{self.name}/relay/"
        try:
            async with self._session.get(
                self.root_url + "register", json={"url": relay}
            ) as resp:
                status = resp.status
                payload = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            return web.json_response({"err": "Root Unreachable"}, status=502)
        if (
            status == 200
            and isinstance(payload, dict)
            and payload.get("client_id")
        ):
            self._workers[str(payload["client_id"])] = _WorkerRoute(
                url=worker_url, key=str(payload.get("key") or "")
            )
            self.metrics.inc("edge_registers_proxied")
            self.metrics.set_gauge("edge_cohort_size", len(self._workers))
        return web.json_response(payload, status=status)

    async def handle_heartbeat(self, request: web.Request) -> web.Response:
        try:
            data = await read_json_capped(request)
        except BodyTooLarge as exc:
            return web.json_response(
                {"err": "Body Too Large", "limit_bytes": exc.limit},
                status=413,
            )
        try:
            async with self._session.get(
                self.root_url + "heartbeat", json=data
            ) as resp:
                status = resp.status
                body = await resp.read()
                ctype = resp.content_type
        except (aiohttp.ClientError, asyncio.TimeoutError):
            return web.json_response({"err": "Root Unreachable"}, status=502)
        self.metrics.inc("edge_heartbeats_proxied")
        return web.Response(body=body, status=status, content_type=ctype)

    def _auth_worker(self, request: web.Request) -> Optional[str]:
        """client_id when the query credentials match a worker this edge
        registered; None otherwise (the worker re-registers on 401 and
        the route re-forms through whatever tier answered)."""
        cid = request.query.get("client_id", "")
        route = self._workers.get(cid)
        if route is None or not route.key or (
            route.key != request.query.get("key", "")
        ):
            return None
        return cid

    # -- downlink: content-addressed blob cache ------------------------
    async def handle_round_blob(self, request: web.Request) -> web.Response:
        if self._auth_worker(request) is None:
            return web.json_response({"err": "Unauthorized"}, status=401)
        digest = request.match_info["digest"]
        hit = digest in self.blob_cache
        data = await self._ensure_blob(digest, self._blob_sizes.get(digest))
        if data is None:
            return web.json_response({"err": "Unknown Blob"}, status=404)
        if hit:
            self.metrics.inc("edge_blob_hits")
        # Range/ETag semantics mirror handle_round_blob at the root —
        # the worker's resume logic must not care which tier serves it
        total = len(data)
        headers = {"Accept-Ranges": "bytes", "ETag": f'"{digest}"'}
        status, start, end = 200, 0, total
        range_hdr = request.headers.get("Range")
        if range_hdr is not None:
            m = self._RANGE_RE.match(range_hdr.strip())
            if m:
                start = int(m.group(1))
                end = int(m.group(2)) + 1 if m.group(2) else total
            if not m or start >= end or end > total:
                headers["Content-Range"] = f"bytes */{total}"
                return web.Response(status=416, headers=headers)
            status = 206
            headers["Content-Range"] = f"bytes {start}-{end - 1}/{total}"
            if start > 0:
                self.metrics.inc("edge_range_resumes")
        payload = data[start:end]
        self.metrics.inc("edge_bytes_served", len(payload))
        return web.Response(
            body=payload, status=status,
            content_type=wire.CONTENT_TYPE, headers=headers,
        )

    async def _ensure_blob(
        self, digest: str, size: Optional[int]
    ) -> Optional[bytes]:
        """Cache lookup with single-flight root fetch: N workers
        stampeding a cold digest produce ONE upstream download — that
        C→E fan-out collapse is the downlink half of this tier."""
        entry = self.blob_cache.get(digest)
        if entry is not None:
            return entry[0]
        fut = self._blob_waits.get(digest)
        if fut is not None:
            return await asyncio.shield(fut)
        fut = asyncio.get_running_loop().create_future()
        self._blob_waits[digest] = fut
        data: Optional[bytes] = None
        try:
            data = await self._fetch_blob_from_root(digest, size)
            if data is not None:
                self.blob_cache.put(data, kind="full")
                self.metrics.set_gauge(
                    "edge_cache_bytes", self.blob_cache.total_bytes
                )
        finally:
            self._blob_waits.pop(digest, None)
            if not fut.done():
                fut.set_result(data)
        return data

    async def _fetch_blob_from_root(
        self, digest: str, size: Optional[int], max_attempts: int = 6
    ) -> Optional[bytes]:
        """Range-resumable, digest-verified pull of one blob from the
        root (the worker's ``_fetch_blob`` contract, with edge
        credentials). Without a declared size (a digest this edge never
        saw an envelope for) the buffer can't be trusted across
        attempts, so failures restart from zero."""
        await self._ensure_registered()
        buf = bytearray()
        with self.tracer.span(
            "edge_blob_fetch", digest=digest[:12]
        ) as sp, self.metrics.timer("edge_blob_fetch_s"):
            for attempt in range(max_attempts):
                if self._closed:
                    break
                cid = self.client_id
                url = self.root_url + f"round_blob/{digest}?{self._creds()}"
                headers = trace_headers()
                if buf:
                    headers["Range"] = f"bytes={len(buf)}-"
                    self.metrics.inc("edge_range_resumes")
                try:
                    async with self._session.get(
                        url, headers=headers
                    ) as resp:
                        if resp.status == 200 and buf:
                            buf.clear()  # server ignored the Range
                        if resp.status in (200, 206):
                            async for chunk in resp.content.iter_chunked(
                                1 << 16
                            ):
                                buf.extend(chunk)
                                if size is not None and len(buf) > size:
                                    break
                        elif resp.status in (404, 410):
                            sp.set(outcome="gone")
                            self.metrics.inc("edge_blob_fetch_failed")
                            return None
                        elif resp.status == 401:
                            self._invalidate_credentials(cid)
                            await self._ensure_registered()
                            buf.clear()
                except (aiohttp.ClientError, asyncio.TimeoutError):
                    pass
                complete = (
                    len(buf) == size if size is not None else len(buf) > 0
                )
                if complete and (
                    hashlib.sha256(bytes(buf)).hexdigest() == digest
                ):
                    self.metrics.inc("edge_blob_fetches")
                    self.metrics.inc("edge_bytes_fetched", len(buf))
                    sp.set(bytes=len(buf), attempts=attempt + 1)
                    return bytes(buf)
                if size is None or (size is not None and len(buf) >= size):
                    # digest mismatch or unsized partial: unresumable
                    buf.clear()
                await asyncio.sleep(
                    min(0.2 * 2 ** attempt, 2.0) * (0.5 + random.random() / 2)
                )
            sp.set(outcome="exhausted")
        self.metrics.inc("edge_blob_fetch_failed")
        return None

    # -- root→worker relay ---------------------------------------------
    async def handle_relay(self, request: web.Request) -> web.Response:
        """Forward one root→worker control POST (``round_start``,
        ``secure_*``) to the worker the root addressed by query
        ``client_id``. An unknown worker answers 404 ON PURPOSE: the
        root drops the client, its next heartbeat 401s, and it
        re-registers through whichever tier is alive — the stale relay
        route self-heals instead of silently eating notifies."""
        tail = request.match_info["tail"]
        cid = request.query.get("client_id", "")
        route = self._workers.get(cid)
        if route is None:
            return web.json_response({"err": "Unknown Worker"}, status=404)
        try:
            body = await read_body_capped(
                request, self.max_upload_bytes or (1 << 30)
            )
        except BodyTooLarge as exc:
            return web.json_response(
                {"err": "Body Too Large", "limit_bytes": exc.limit},
                status=413,
            )
        if tail == "round_start":
            # learn the round (roll fold state, prefetch the blob)
            # BEFORE forwarding: the worker may start fetching the
            # moment it acks, and the single-flight cache wants the
            # fetch already in motion
            self._observe_envelope(body)
        # re-read after the body-read suspension: the worker may have
        # re-registered (new route) while the POST body streamed in
        route = self._workers.get(cid)
        if route is None:
            return web.json_response({"err": "Unknown Worker"}, status=404)
        ctx = tracing.parse_traceparent(request.headers.get("traceparent"))
        token = tracing.activate(ctx[0], ctx[1]) if ctx is not None else None
        qs = request.query_string
        url = route.url.rstrip("/") + "/" + tail + (f"?{qs}" if qs else "")
        try:
            with self.tracer.span(
                "edge_relay", target=tail, client=cid
            ) as sp, self.metrics.timer("edge_relay_s"):
                try:
                    async with self._session.post(
                        url, data=body,
                        headers=trace_headers({
                            "Content-Type": request.content_type
                            or "application/octet-stream"
                        }),
                    ) as resp:
                        payload = await resp.read()
                        ctype = resp.content_type
                        sp.set(status=resp.status)
                        if tail == "round_start":
                            self.metrics.inc("edge_relay_notifies")
                            r = self._round
                            if resp.status == 200 and r is not None:
                                r.notified.add(cid)
                                self._set_pending_gauge(r)
                        return web.Response(
                            body=payload, status=resp.status,
                            content_type=ctype,
                        )
                except (aiohttp.ClientError, asyncio.TimeoutError):
                    sp.set(status=None)
                    self.metrics.inc("edge_relay_failed")
                    # 502, not 404: a transient worker hiccup must not
                    # get it evicted from the root registry
                    return web.json_response(
                        {"err": "Worker Unreachable"}, status=502
                    )
        finally:
            if token is not None:
                tracing.deactivate(token)

    async def handle_edge_callback(
        self, request: web.Request
    ) -> web.Response:
        """The root's callback endpoint for the edge's OWN registry
        entry. The edge is infrastructure, not a trainer: it declines
        every cohort invitation with 409 (a 404 would drop its
        credentials). A ``round_start`` body is still a fresh envelope
        — observe it opportunistically."""
        if request.match_info["tail"] == "round_start":
            try:
                body = await read_body_capped(
                    request, self.max_upload_bytes or (1 << 30)
                )
            except BodyTooLarge:
                return web.json_response({"err": "Body Too Large"},
                                         status=413)
            self._observe_envelope(body)
        return web.json_response({"err": "Edge Aggregator"}, status=409)

    # -- round state ---------------------------------------------------
    def _observe_envelope(self, body: bytes) -> None:
        """Parse a v2 notify envelope and roll per-round fold state.
        Legacy push bodies (raw tensors) and malformed JSON are ignored
        — uploads for rounds the edge never learned proxy through."""
        try:
            env = json.loads(body.decode("utf-8"))
            round_name = str(env["update_name"])
            n_epoch = int(env["n_epoch"])
            digest = str(env["blob"]["digest"])
            size = int(env["blob"]["size"])
        except (UnicodeDecodeError, ValueError, TypeError, KeyError):
            return
        r = self._round
        if r is not None and r.round_name == round_name:
            return
        if r is not None:
            r.cancel_tasks()
            if not r.shipped and r.contributors:
                # the root rolled the round under our feet (watchdog
                # force-end, abort): the partial can never land
                self.metrics.inc("edge_partials_abandoned")
            self._ledger_round(r)
        secure = env.get("secure") is not None
        encoded = bool(env.get("encoding"))
        r = _EdgeRound(
            round_name, n_epoch, digest, size,
            proxy_only=secure or encoded, secure=secure,
        )
        self._round = r
        # cache retention: this envelope's digests (full + delta hops)
        # survive the roll; everything older is dropped
        sizes: Dict[str, int] = {digest: size}
        for hop in [env.get("delta")] + list(env.get("delta_chain") or []):
            if isinstance(hop, dict):
                try:
                    sizes[str(hop["digest"])] = int(hop["size"])
                except (KeyError, ValueError, TypeError):
                    continue
        self._blob_sizes = sizes
        self.blob_cache.retain(sizes)
        self.metrics.set_gauge(
            "edge_cache_bytes", self.blob_cache.total_bytes
        )
        self._set_pending_gauge(r)
        r.deadline_task = asyncio.ensure_future(
            self._ship_later(r, self.flush_after_s, force=True)
        )
        if not r.proxy_only:
            asyncio.ensure_future(self._prepare_round(r))

    async def _prepare_round(self, r: _EdgeRound) -> None:
        """Prefetch the round blob and decode the dense validation
        template the fold path checks shapes against. A failed prefetch
        degrades the round to proxy-only — never blocks it."""
        try:
            t_fetch0 = time.monotonic()
            data = await self._ensure_blob(r.digest, r.size)
            r.fetch_s = time.monotonic() - t_fetch0
            if data is not None:
                r.template = (await asyncio.to_thread(wire.decode, data))[0]
            else:
                r.proxy_only = True
        except Exception:
            r.proxy_only = True
        finally:
            r.template_ready.set()

    def _set_pending_gauge(self, r: _EdgeRound) -> None:
        self.metrics.set_gauge(
            "edge_round_pending",
            max(0, len(r.notified - set(r.contributors))),
        )

    def _ledger_round(self, r: _EdgeRound) -> None:
        """Fold one retired round into this edge's cohort ledger:
        contributors reported (with their self-reported timings and
        body size), notified-but-silent workers straggled. Best-effort
        — health accounting must never break a round roll."""
        if not r.notified and not r.contributors:
            return
        try:
            responses = {
                cid: {
                    "n_samples": c.get("n_samples"),
                    "loss_history": c.get("loss_history"),
                    "upload_bytes": c.get("bytes"),
                    "timings": c.get("timings"),
                    "compute": c.get("compute"),
                }
                for cid, c in r.contributors.items()
            }
            self.fleet.record_round(
                r.round_name, r.notified, r.notified, responses
            )
        except Exception:
            logging.getLogger(__name__).exception(
                "edge fleet ledger record failed"
            )

    # -- uplink: cohort ingest + fold ----------------------------------
    async def handle_update(self, request: web.Request) -> web.Response:
        cid = self._auth_worker(request)
        if cid is None:
            return web.json_response({"err": "Unauthorized"}, status=401)
        try:
            body = await read_body_capped(request, self.max_upload_bytes)
        except BodyTooLarge:
            return web.json_response({"err": "Payload Too Large"},
                                     status=413)
        ctx = tracing.parse_traceparent(request.headers.get("traceparent"))
        if ctx is None:
            return await self._ingest_cohort_update(
                cid, body, request.content_type
            )
        with self.tracer.span(
            "edge_ingest", trace_id=ctx[0], parent_id=ctx[1],
            client=cid, bytes=len(body),
        ):
            return await self._ingest_cohort_update(
                cid, body, request.content_type
            )

    async def _ingest_cohort_update(
        self, client_id: str, body: bytes, content_type
    ) -> web.Response:
        """Decode off-loop, then fold into the round partial — or proxy
        upstream when this edge cannot own the update (unknown round,
        compressed body, already shipped). Masked bodies 409: the
        worker pins those direct, so an arrival here is a downgrade
        guard firing, not a route."""

        def decode():
            tensors, meta = wire.decode_any(
                body, content_type, allow_pickle=False
            )
            return tensors, meta

        fut = self._pipe.submit_decode(decode)
        if fut is None:
            return web.json_response(
                {"err": "Ingest Queue Full"}, status=429,
                headers={"Retry-After": "1"},
            )
        try:
            tensors, meta = await fut
        except asyncio.CancelledError:
            raise
        except Exception:
            return web.json_response({"err": "Bad Payload"}, status=400)

        # round snapshot taken AFTER the decode suspension: a roll that
        # landed mid-decode must route this update against the round
        # that is actually open now
        r = self._round
        if meta.get("secure") or (r is not None and r.secure):
            # partial-folding ring elements breaks unmasking — refuse
            # loudly; the worker's 409 handler marks this route down
            # and re-delivers direct to the root
            self.metrics.inc("edge_updates_refused_secure")
            return web.json_response(
                {"err": "Secure Round Requires Direct Upload"}, status=409
            )
        round_name = str(meta.get("update_name") or "")
        if (
            r is None
            or r.proxy_only
            or r.shipped
            or r.shipping
            or round_name != r.round_name
            or meta.get("compressed")
        ):
            return await self._proxy_update(client_id, body, content_type)
        try:
            # the only await between the snapshot and here is a
            # return-await in the branch above (branch-sensitive BTL003
            # knows that path cannot fall through); staleness is
            # re-checked with the identity test right after this wait
            await asyncio.wait_for(
                r.template_ready.wait(), timeout=30.0
            )
        except asyncio.TimeoutError:
            return await self._proxy_update(client_id, body, content_type)
        if (
            self._round is not r or r.template is None or r.shipped
            or r.shipping
        ):
            # the round rolled (or the partial started shipping) while
            # we waited on the template: the root owns this update now
            return await self._proxy_update(client_id, body, content_type)

        try:
            n_samples = float(meta.get("n_samples", 0))
            losses = [float(x) for x in meta.get("loss_history", [])]
            update_id = (
                str(meta["update_id"]) if meta.get("update_id") else None
            )
        except (TypeError, ValueError):
            return web.json_response({"err": "Bad Payload"}, status=400)
        if not (n_samples > 0) or not np.isfinite(n_samples):
            return web.json_response({"err": "Bad Payload"}, status=400)
        for k, ref in r.template.items():
            v = tensors.get(k)
            if v is None or tuple(np.shape(v)) != tuple(np.shape(ref)):
                return web.json_response({"err": "Bad Payload"}, status=400)

        if update_id is not None and update_id in r.update_ids:
            # at-least-once redelivery of an already-folded update
            return web.Response(text="OK")
        if client_id in r.contributors:
            # same client, NEW update id: first accepted result wins
            # (mirrors the root's repeat_updates_ignored contract)
            return web.Response(text="OK")

        # acceptance point: ALL bookkeeping (including the pending-fold
        # increment) lands before the await so a ship racing this
        # accept either sees shipping already set (we proxied above) or
        # drains our fold before computing the partial mean
        if update_id is not None:
            r.update_ids.add(update_id)
        entry = {
            "n_samples": n_samples,
            "update_id": update_id,
            "loss_history": losses,
            "bytes": len(body),
        }
        timings = meta.get("timings")
        if isinstance(timings, dict):
            # worker self-reported wall times, shipped upstream in the
            # partial's contributor set (the root sanitizes values)
            entry["timings"] = timings
        compute = meta.get("compute")
        if isinstance(compute, dict):
            # per-round compute record (obs/compute.py) — same contract
            # as timings: pass through verbatim, the root sanitizes
            entry["compute"] = compute
        r.contributors[client_id] = entry
        r.pending_folds += 1
        self.metrics.inc("edge_updates_folded")
        self._set_pending_gauge(r)
        template = r.template

        def fold():
            t_fold0 = time.perf_counter()
            payload = {
                k: np.asarray(tensors[k], np.float32) for k in template
            }
            r.acc.add(payload, n_samples)
            # fold_shards=1: one fold worker, so += never races
            r.fold_s += time.perf_counter() - t_fold0

        try:
            await self._pipe.submit_fold(0, fold)
        finally:
            r.pending_folds -= 1
        self._maybe_ship(r)
        return web.Response(text="OK")

    async def _proxy_update(
        self, client_id: str, body: bytes, content_type
    ) -> web.Response:
        """Pass one update through to the root under the WORKER's own
        credentials (the root registered it; the edge only relayed).
        A transport failure answers 409 so the worker marks this route
        down and re-delivers direct."""
        route = self._workers.get(client_id)
        if route is None:
            return web.json_response({"err": "Unauthorized"}, status=401)
        url = (
            self.root_url
            + f"update?client_id={client_id}&key={route.key}"
        )
        try:
            async with self._session.post(
                url, data=body,
                headers=trace_headers({
                    "Content-Type": content_type or wire.CONTENT_TYPE
                }),
            ) as resp:
                payload = await resp.read()
                self.metrics.inc("edge_updates_proxied")
                return web.Response(
                    body=payload, status=resp.status,
                    content_type=resp.content_type,
                )
        except (aiohttp.ClientError, asyncio.TimeoutError):
            return web.json_response(
                {"err": "Root Unreachable Via Edge"}, status=409
            )

    # -- uplink: chunked reassembly (worker→edge) ----------------------
    async def handle_update_chunk(
        self, request: web.Request
    ) -> web.Response:
        """Same offset-committed contract as the root's chunk endpoint;
        the assembled body enters :meth:`_ingest_cohort_update` exactly
        as a single POST would have."""
        cid = self._auth_worker(request)
        if cid is None:
            return web.json_response({"err": "Unauthorized"}, status=401)
        update_id = request.match_info["update_id"]
        try:
            offset = int(request.query["offset"])
            total = int(request.query["total"])
        except (KeyError, ValueError):
            return web.json_response({"err": "Bad Chunk Framing"},
                                     status=400)
        if total <= 0 or offset < 0 or offset > total:
            return web.json_response({"err": "Bad Chunk Framing"},
                                     status=400)
        if self.max_upload_bytes is not None and total > self.max_upload_bytes:
            return web.json_response({"err": "Payload Too Large"},
                                     status=413)
        key = (cid, update_id)
        sess = self._chunks.get(key)
        if sess is None:
            if offset != 0:
                return web.json_response(
                    {"err": "Unknown Chunk Session", "offset": 0}, status=409
                )
            sess = _ChunkSession(cid, update_id, total)
            sess.content_type = request.content_type or wire.CONTENT_TYPE
            self._chunks[key] = sess
        if sess.total != total:
            self._chunks.pop(key, None)
            return web.json_response({"err": "Inconsistent Total"},
                                     status=400)
        if sess.busy:
            return web.json_response(
                {"err": "Chunk In Flight", "offset": sess.offset}, status=409
            )
        if offset != sess.offset:
            return web.json_response(
                {"err": "Offset Mismatch", "offset": sess.offset}, status=409
            )
        sess.busy = True
        try:
            try:
                chunk = await read_body_capped(request, sess.total - offset)
            except BodyTooLarge:
                return web.json_response({"err": "Chunk Overruns Total"},
                                         status=413)
            sess.buf.extend(chunk)
            if sess.offset < sess.total:
                return web.json_response({"offset": sess.offset})
            ctx = tracing.parse_traceparent(
                request.headers.get("traceparent")
            )
            if ctx is None:
                resp = await self._ingest_cohort_update(
                    cid, bytes(sess.buf), sess.content_type
                )
            else:
                with self.tracer.span(
                    "edge_ingest", trace_id=ctx[0], parent_id=ctx[1],
                    client=cid, bytes=sess.total, chunked=True,
                ):
                    resp = await self._ingest_cohort_update(
                        cid, bytes(sess.buf), sess.content_type
                    )
        finally:
            sess.busy = False
        if resp.status == 429:
            return resp  # keep the session; the retry re-sends one frame
        self._chunks.pop(key, None)
        return resp

    async def handle_update_chunk_probe(
        self, request: web.Request
    ) -> web.Response:
        cid = self._auth_worker(request)
        if cid is None:
            return web.json_response({"err": "Unauthorized"}, status=401)
        sess = self._chunks.get((cid, request.match_info["update_id"]))
        offset = sess.offset if sess is not None else 0
        return web.json_response(
            {"offset": offset, "total": sess.total if sess else None},
            headers={"Upload-Offset": str(offset)},
        )

    # -- ship: one partial upstream ------------------------------------
    def _maybe_ship(self, r: _EdgeRound) -> None:
        """Arm the settle timer once every notified worker has
        reported. The delay absorbs a straggler notify landing just
        after the last accept; the ``flush_after_s`` deadline task
        bounds the wait when part of the cohort never reports."""
        if r.shipped or r.shipping:
            return
        if not r.notified or not (
            r.notified <= set(r.contributors)
        ):
            return
        if r.settle_task is not None and not r.settle_task.done():
            r.settle_task.cancel()
        r.settle_task = asyncio.ensure_future(
            self._ship_later(r, self.ship_settle_s)
        )

    async def _ship_later(
        self, r: _EdgeRound, delay: float, force: bool = False
    ) -> None:
        try:
            await asyncio.sleep(delay)
        except asyncio.CancelledError:
            return
        if r.shipped or r.shipping or self._round is not r:
            return
        if not force and not (
            r.notified and r.notified <= set(r.contributors)
        ):
            return
        await self._ship_partial(r)

    async def _ship_partial(self, r: _EdgeRound) -> None:
        """Encode the partial (cohort mean + Σ weight + contributor
        set) and deliver it upstream as ONE update. 200 from the root
        is the cohort's acceptance; anything terminal still marks the
        round shipped so stragglers proxy through instead of folding
        into a partial that will never leave."""
        if r.shipped or r.shipping:
            return
        # from this point every new upload proxies through (the ingest
        # path checks `shipping`), so contributors/acc only have to
        # settle, not stay open
        r.shipping = True
        try:
            if not r.contributors:
                r.shipped = True
                return
            # drain accepts whose fold is still queued in the pipeline:
            # they are already in `contributors`, so the mean must
            # include their tensors or the root would credit clients
            # this partial never aggregated
            for _ in range(3000):
                if not r.pending_folds:
                    break
                await asyncio.sleep(0.01)
            mean = await asyncio.to_thread(r.acc.mean)
            if mean is None:
                r.shipped = True
                return
            # per-round phase wall times for the root's SLO counter
            # deltas. "settle" is envelope→ship-start (fold + wait);
            # "ship_prev" is the PREVIOUS round's measured upstream
            # delivery — this round's isn't known until after encode.
            phase_s = {
                "fold": round(r.fold_s, 6),
                "blob_fetch": round(r.fetch_s, 6),
                "settle": round(time.monotonic() - r.t0, 6),
            }
            if self._last_ship_s is not None:
                phase_s["ship_prev"] = round(self._last_ship_s, 6)
            meta = {
                "update_name": r.round_name,
                "n_samples": float(r.acc.total_weight),
                "loss_history": [],
                "update_id": r.ship_update_id,
                "edge_partial": {
                    "edge": self.edge_name,
                    "contributors": r.contributors,
                    "phase_s": phase_s,
                },
            }
            body = await asyncio.to_thread(wire.encode, mean, meta)
            trace_id = tracing.make_trace_id(self.name, r.round_name)
            with self.tracer.span(
                "edge_partial_upload", trace_id=trace_id,
                parent_id=tracing.root_span_id(trace_id),
                round=r.round_name, contributors=len(r.contributors),
                bytes=len(body),
            ) as sp, self.metrics.timer("edge_partial_ship_s"):
                t_ship0 = time.monotonic()
                status = await self._deliver_upstream(body, r.ship_update_id)
                self._last_ship_s = time.monotonic() - t_ship0
                sp.set(status=status)
            r.shipped = True
            if status == 200:
                self.metrics.inc("edge_partials_shipped")
            elif status == 409:
                # the root refuses partials for this round (secure or
                # buffered aggregation): misconfiguration made visible
                self.metrics.inc("edge_partial_refused")
            else:
                self.metrics.inc("edge_partial_ship_failed")
            self._set_pending_gauge(r)
            asyncio.ensure_future(self._ship_spans(trace_id))
        finally:
            r.shipping = False

    async def _deliver_upstream(
        self, body: bytes, update_id: str, max_attempts: int = 6
    ) -> Optional[int]:
        """Deliver the encoded partial to the root with bounded retries
        — chunked when configured and the body is large, single POST
        otherwise. Returns the final HTTP status (None = transport
        failure exhausted the attempts)."""
        backoff = 0.5
        status: Optional[int] = None
        for _ in range(max_attempts):
            if self._closed:
                return status
            await self._ensure_registered()
            cid = self.client_id
            retry_after: Optional[float] = None
            chunked = (
                self.upload_chunk_bytes is not None
                and len(body) > self.upload_chunk_bytes
            )
            if chunked:
                status, retry_after = await self._ship_chunked(
                    body, update_id
                )
            else:
                url = self.root_url + f"update?{self._creds()}"
                try:
                    async with self._session.post(
                        url, data=body,
                        headers=trace_headers(
                            {"Content-Type": wire.CONTENT_TYPE}
                        ),
                    ) as resp:
                        status = resp.status
                        ra = resp.headers.get("Retry-After")
                        try:
                            retry_after = float(ra) if ra else None
                        except ValueError:
                            retry_after = None
                except (aiohttp.ClientError, asyncio.TimeoutError):
                    status = None
            if status in (200, 400, 409, 410, 413):
                return status  # terminal either way
            if status == 401:
                # root restarted: rejoin and retry (no-op if a parallel
                # task already re-registered during our await)
                self._invalidate_credentials(cid)
            delay = backoff * (0.5 + random.random() / 2)
            if retry_after is not None:
                delay = max(delay, retry_after)
            await asyncio.sleep(delay)
            backoff = min(backoff * 2, MAX_BACKOFF)
        return status

    async def _ship_chunked(
        self, body: bytes, update_id: str
    ) -> Tuple[Optional[int], Optional[float]]:
        """One chunked delivery attempt against the root's resumable
        endpoint (probe → ordered PUTs, 409 = authoritative offset
        resync) — the worker's algorithm with edge credentials."""
        total = len(body)
        base = (
            self.root_url + f"update_chunk/{update_id}?{self._creds()}"
        )
        try:
            async with self._session.get(
                base, headers=trace_headers()
            ) as resp:
                if resp.status == 401:
                    return 401, None
                if resp.status == 200:
                    data = await resp.json()
                    offset = max(0, min(int(data.get("offset", 0)), total))
                else:
                    offset = 0
        except (aiohttp.ClientError, asyncio.TimeoutError,
                TypeError, ValueError):
            return None, None
        resyncs = 0
        while True:
            end = min(offset + int(self.upload_chunk_bytes), total)
            url = base + f"&offset={offset}&total={total}"
            try:
                async with self._session.put(
                    url, data=body[offset:end],
                    headers=trace_headers(
                        {"Content-Type": wire.CONTENT_TYPE}
                    ),
                ) as resp:
                    if resp.status == 409:
                        resyncs += 1
                        if resyncs > 8:
                            return None, None
                        try:
                            data = await resp.json()
                            offset = max(
                                0, min(int(data.get("offset", 0)), total)
                            )
                        except (TypeError, ValueError):
                            return None, None
                        continue
                    if resp.status != 200:
                        ra = resp.headers.get("Retry-After")
                        try:
                            return resp.status, float(ra) if ra else None
                        except ValueError:
                            return resp.status, None
                    if end >= total:
                        return 200, None
                    try:
                        data = await resp.json()
                        offset = min(
                            total, max(end, int(data.get("offset", end)))
                        )
                    except (TypeError, ValueError):
                        offset = end
            except (aiohttp.ClientError, asyncio.TimeoutError):
                return None, None

    # -- tracing -------------------------------------------------------
    async def handle_trace_spans(self, request: web.Request) -> web.Response:
        """Pass worker span batches through to the root untouched (the
        query already carries the worker's root credentials)."""
        try:
            body = await read_body_capped(request, 8 << 20)
        except BodyTooLarge:
            return web.json_response({"err": "Body Too Large"}, status=413)
        qs = request.query_string
        url = self.root_url + "trace_spans" + (f"?{qs}" if qs else "")
        try:
            async with self._session.post(
                url, data=body,
                headers={"Content-Type": "application/json"},
            ) as resp:
                payload = await resp.read()
                return web.Response(
                    body=payload, status=resp.status,
                    content_type=resp.content_type,
                )
        except (aiohttp.ClientError, asyncio.TimeoutError):
            return web.json_response({"err": "Root Unreachable"}, status=502)

    async def _ship_spans(self, trace_id: str) -> None:
        """Ship this edge's own finished spans for one round upstream —
        best-effort, after the partial lands, so the root's trace
        endpoint can serve the whole tree in one document."""
        spans = self.tracer.drain(trace_id)
        if not spans:
            return
        url = self.root_url + f"trace_spans?{self._creds()}"
        try:
            async with self._session.post(url, json=spans) as resp:
                if resp.status == 200:
                    self.metrics.inc("trace_spans_shipped", len(spans))
                else:
                    self.metrics.inc("trace_ship_failed")
        except (aiohttp.ClientError, asyncio.TimeoutError):
            self.metrics.inc("trace_ship_failed")

    # -- observability -------------------------------------------------
    async def handle_metrics(self, request: web.Request) -> web.Response:
        self.fleet.export_gauges(self.metrics)
        snap = self.metrics.snapshot()
        snap["edge"] = {
            "edge_name": self.edge_name,
            "workers": len(self._workers),
            "round": self._round.round_name if self._round else None,
            "round_shipped": self._round.shipped if self._round else None,
            "cache_bytes": self.blob_cache.total_bytes,
        }
        return web.json_response(snap)

    async def handle_metrics_history(
        self, request: web.Request
    ) -> web.Response:
        hist = self.metrics.history()
        return web.json_response({
            "interval_s": self.metrics_history_interval_s,
            "samples": len(hist),
            "history": hist,
        })

    async def handle_fleet_health(
        self, request: web.Request
    ) -> web.Response:
        return web.json_response(json_clean(self.fleet.health_snapshot()))

    async def handle_alerts(self, request: web.Request) -> web.Response:
        """``GET /{name}/alerts`` — this edge's rule states (same
        payload shape as the root's endpoint)."""
        return web.json_response(json_clean(self.alerts.status_snapshot()))
