"""Secure-aggregation wire protocol: key agreement, masking, recovery.

The reference manager observes every client's raw weights
(reference manager.py:95-126). This module gives the HTTP control plane
a Bonawitz-style protocol on top of the modular-masking primitives in
:mod:`baton_tpu.ops.secure_agg`, so the manager only ever learns the
*sum* of client updates:

1. **Key agreement** — per round, every cohort member generates a
   Diffie-Hellman keypair (RFC 3526 group 14, 2048-bit MODP) and sends
   the public key to the manager (``POST /{name}/secure_keys``); the
   manager broadcasts the cohort's public-key directory inside
   ``round_start``. Each pair (i, j) then shares a seed
   ``SHA-256(round_name ‖ DH(sk_i, pk_j))`` that the server cannot
   compute.
2. **Masked upload** — each client quantizes its sample-weighted update
   into Z_2^64 (fixed point) and adds one Philox-derived uint64 mask
   per pair: ``+mask`` when its client_id sorts before the peer's,
   ``−mask`` otherwise. Any single upload is uniform noise to the
   server; the modular sum over the full cohort is exactly the sum of
   the quantized updates. The 64-bit ring (vs the 32-bit offline
   primitive in ops/secure_agg.py) buys headroom for *sample-weighted*
   sums: at 16 fractional bits, Σᵢ nᵢ·|θ| may reach 2^47 before
   wrapping — ample for any real federation, where 2^15 (the 32-bit
   budget) is overflowed by a single 40k-sample client.
3. **Dropout recovery** — if cohort members vanish between key exchange
   and upload, every reporter's upload still carries uncancelled masks
   toward them. The manager asks each reporter to reveal its *pairwise
   seed with the dropped client only* (``GET /{name}/reveal``), rebuilds
   those masks, and cancels the residue. Reporters' own pairwise seeds
   (and all secret keys) never leave the clients.

Threat model — stated precisely, because it is narrower than full
Bonawitz: the server is **honest-but-curious and follows the protocol**
(it only requests reveals for clients that genuinely never reported),
and clients do not collude with it. Under that model the server learns
only the cohort sum. A server that *deviates* by falsely claiming a
live reporter dropped can collect the other reporters' seeds toward it
and unmask that one client's update; closing that hole requires the
full protocol's double masking (per-client self-mask b_i) with Shamir
shares so each peer reveals, per client, EITHER the pairwise seed OR
the self-mask share — never both. Workers bound the damage of a
deviating server with a per-round reveal budget
(``max_reveal_fraction``): at most that fraction of the cohort can be
named "dropped" before the worker refuses further reveals and the
round aborts. A reporter that dies *during* recovery also makes the
round unrecoverable; the manager then aborts and keeps the previous
global params, which is safe. Round-binding the seed hash prevents
cross-round mask replay.
"""

from __future__ import annotations

import hashlib
import secrets
from typing import Dict, Mapping, Sequence, Tuple

import numpy as np

from baton_tpu.ops.secure_agg import DEFAULT_SCALE_BITS

_RING_BITS = 64
_RING = 1 << _RING_BITS


def quantize64(
    state: Mapping[str, np.ndarray], scale_bits: int = DEFAULT_SCALE_BITS
) -> Dict[str, np.ndarray]:
    """Float state dict -> uint64 fixed point (two's complement in
    Z_2^64). int64 intermediates hold scale_bits=16 magnitudes up to
    2^47 exactly — the sample-weighted sums this protocol ships."""
    scale = float(1 << scale_bits)
    return {
        k: np.round(np.asarray(v, np.float64) * scale)
        .astype(np.int64)
        .astype(np.uint64)
        for k, v in state.items()
    }


def dequantize64(
    state: Mapping[str, np.ndarray], scale_bits: int = DEFAULT_SCALE_BITS
) -> Dict[str, np.ndarray]:
    """uint64 ring elements -> float64; values >= 2^63 read as negative."""
    scale = float(1 << scale_bits)
    out = {}
    for k, v in state.items():
        signed = np.asarray(v, np.uint64).astype(np.int64)  # two's complement
        out[k] = signed.astype(np.float64) / scale
    return out

# RFC 3526 group 14: 2048-bit MODP prime, generator 2. A fixed,
# nothing-up-my-sleeve group (pi-derived) — the standard choice for
# finite-field DH without external crypto dependencies.
MODP_P = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A637ED6B0BFF5CB6F406B7ED"
    "EE386BFB5A899FA5AE9F24117C4B1FE649286651ECE45B3DC2007CB8A163BF05"
    "98DA48361C55D39A69163FA8FD24CF5F83655D23DCA3AD961C62F356208552BB"
    "9ED529077096966D670C354E4ABC9804F1746C08CA18217C32905E462E36CE3B"
    "E39E772C180E86039B2783A2EC07A28FB5C55DF06F4C52C9DE2BCBF695581718"
    "3995497CEA956AE515D2261898FA051015728E5A8AACAA68FFFFFFFFFFFFFFFF",
    16,
)
MODP_G = 2
_SK_BITS = 256  # exponent size; 2^256 work factor ≫ the group's ~110-bit strength


def dh_keypair() -> Tuple[int, int]:
    """Fresh per-round DH keypair (sk, pk = g^sk mod p)."""
    sk = secrets.randbits(_SK_BITS) | 1
    return sk, pow(MODP_G, sk, MODP_P)


def dh_shared_seed(sk: int, pk_other: int, context: str) -> bytes:
    """32-byte pairwise seed: SHA-256(context ‖ g^(sk_i·sk_j) mod p).

    Symmetric in the pair by DH; ``context`` (the round name) binds masks
    to one round so a replayed upload can't be unmasked with old seeds.
    """
    if not 1 < pk_other < MODP_P - 1:
        raise ValueError("invalid DH public key")
    shared = pow(pk_other, sk, MODP_P)
    return hashlib.sha256(
        context.encode() + b"|" + shared.to_bytes(256, "big")
    ).digest()


def _pair_sign(my_id: str, other_id: str) -> int:
    """Mask sign convention: the lexicographically-smaller client_id adds
    the pair's mask, the larger subtracts it — identical on every party
    with no coordination."""
    if my_id == other_id:
        raise ValueError("no pairwise mask with self")
    return 1 if my_id < other_id else -1


def pair_mask(seed: bytes, template: Mapping[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Deterministic uniform-uint64 mask per tensor from a 32-byte seed.

    Philox (256-bit key = the seed) is counter-based and bit-identical
    across platforms, so client-side masking and server-side dropout
    recovery derive the same stream from the same seed.
    """
    words = np.frombuffer(seed, dtype=np.uint64)  # 4 × uint64
    gen = np.random.Generator(
        np.random.Philox(
            key=words[:2],  # Philox keys are 128-bit
            counter=np.concatenate([words[2:], np.zeros(2, np.uint64)]),
        )
    )
    # one stream, consumed in sorted-name order: client masking and
    # server recovery must draw identical bits even if their state dicts
    # were built in different insertion orders
    out = {}
    for name in sorted(template):
        out[name] = gen.integers(
            0, 1 << 64, size=np.shape(template[name]), dtype=np.uint64
        )
    return out


def mask_state_dict(
    state: Mapping[str, np.ndarray],
    my_id: str,
    pair_seeds: Mapping[str, bytes],
    scale_bits: int = DEFAULT_SCALE_BITS,
) -> Dict[str, np.ndarray]:
    """Client-side: quantize ``state`` and add every pairwise mask.

    ``pair_seeds`` maps each *other* cohort member's client_id to the DH
    seed shared with it. The result is uint64 ring elements — uniform
    noise to anyone missing the seeds.
    """
    out = quantize64(state, scale_bits)
    for other_id, seed in pair_seeds.items():
        sign = _pair_sign(my_id, other_id)
        mask = pair_mask(seed, out)
        for k in out:
            if sign > 0:
                out[k] = (out[k] + mask[k]).astype(np.uint64)
            else:
                out[k] = (out[k] - mask[k]).astype(np.uint64)
    return out


def modular_sum(updates: Sequence[Mapping[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """Σ mod 2^64 over masked uploads (server-side)."""
    total = {k: np.asarray(v, np.uint64).copy() for k, v in updates[0].items()}
    for u in updates[1:]:
        for k in total:
            total[k] = (total[k] + np.asarray(u[k], np.uint64)).astype(np.uint64)
    return total


def dropout_correction(
    dropped_id: str,
    revealed_seeds: Mapping[str, bytes],
    template: Mapping[str, np.ndarray],
) -> Dict[str, np.ndarray]:
    """Server-side: the additive correction cancelling a dropped client.

    Each reporter i's upload contains ``sign(i, d)·mask(seed_id)`` toward
    dropped client d; summing ``sign(d, i)·mask(seed_id)`` over the
    reporters (whose seeds with d they revealed) is exactly the negation
    of the residue.
    """
    corr = {
        k: np.zeros(np.shape(v), np.uint64) for k, v in template.items()
    }
    for reporter_id, seed in revealed_seeds.items():
        sign = _pair_sign(dropped_id, reporter_id)
        mask = pair_mask(seed, template)
        for k in corr:
            if sign > 0:
                corr[k] = (corr[k] + mask[k]).astype(np.uint64)
            else:
                corr[k] = (corr[k] - mask[k]).astype(np.uint64)
    return corr


def unmask_sum(
    masked_sum: Mapping[str, np.ndarray],
    corrections: Sequence[Mapping[str, np.ndarray]],
    scale_bits: int = DEFAULT_SCALE_BITS,
) -> Dict[str, np.ndarray]:
    """Apply dropout corrections and dequantize to float64."""
    total = {k: np.asarray(v, np.uint64).copy() for k, v in masked_sum.items()}
    for corr in corrections:
        for k in total:
            total[k] = (total[k] + np.asarray(corr[k], np.uint64)).astype(np.uint64)
    return dequantize64(total, scale_bits)
