"""Secure-aggregation wire protocol: key agreement, masking, recovery.

The reference manager observes every client's raw weights
(reference manager.py:95-126). This module gives the HTTP control plane
a Bonawitz-style protocol on top of the modular-masking primitives in
:mod:`baton_tpu.ops.secure_agg`, so the manager only ever learns the
*sum* of client updates:

0. **AdvertiseKeys** — per round, every cohort member generates TWO
   Diffie-Hellman keypairs (RFC 3526 group 14, 2048-bit MODP): ``c``
   keys derive the pairwise mask seeds, ``s`` keys encrypt the share
   transport (``POST /{name}/secure_keys``). Each pair (i, j) shares
   seeds ``SHA-256(context ‖ DH(sk_i, pk_j))`` the server cannot
   compute.
1. **ShareKeys** — each member draws a self-mask seed b_i and
   Shamir-shares (t-of-n, honest-majority t = ⌊n/2⌋+1) both b_i and
   its mask secret key c_sk_i across the cohort
   (``POST /{name}/secure_shares``). Share pairs travel sealed under
   the pairwise s-key (encrypt-then-MAC) and are RELAYED by the
   manager inside the ``round_start`` broadcast — opaque to it.
   Members that fail this phase never distributed shares, so they are
   excluded from the masking cohort outright.
2. **MaskedInputCollection** — each client uploads its sample-weighted
   update quantized into Z_2^64 (fixed point) plus one Philox-derived
   uint64 mask per pair (``+`` when its client_id sorts first, ``−``
   otherwise) plus its self mask PRG(b_i). Any single upload — even
   with every pairwise seed known — is uniform noise without b_i. The
   64-bit ring (vs the 32-bit offline primitive in ops/secure_agg.py)
   buys headroom for sample-weighted sums: at 16 fractional bits,
   Σᵢ nᵢ·|θ| may reach 2^47 before wrapping.
3. **Unmasking** — the server partitions the masking cohort into
   survivors (reporters) and dropped, and asks every reporter ONCE for
   its share bundle (``POST /{name}/secure_unmask``): per peer, EITHER
   the self-mask share (survivors) OR the mask-key share (dropped) —
   never both, and the partition is pinned for the round. From ≥t
   shares each, the server reconstructs dropped members' c_sk (to
   cancel their residual pairwise masks) and survivors' b_i (to remove
   self masks), then dequantizes the sum.

Threat model (Bonawitz et al. 2017, honest-but-curious single server,
honest majority of clients): the server learns only the survivors'
sum. Fabricated dropout claims are useless — naming a live reporter
"dropped" forfeits its self-mask share under the either-or rule, so
its upload stays masked by PRG(b_i); asking again with a different
partition is refused (pinning). Up to n−t unmask responders may fail
and the round still opens; below the threshold the manager aborts and
the previous global params stand, which is safe. Round-binding every
seed hash prevents cross-round mask replay. (Active network attackers
impersonating the server/clients would additionally need a PKI for
signed key advertisements — out of scope, as in the paper's
semi-honest variant.)
"""

from __future__ import annotations

import hashlib
import secrets
from typing import Dict, Mapping, Optional, Sequence, Tuple

import numpy as np

from baton_tpu.ops.secure_agg import DEFAULT_SCALE_BITS

_RING_BITS = 64
_RING = 1 << _RING_BITS


def quantize64(
    state: Mapping[str, np.ndarray], scale_bits: int = DEFAULT_SCALE_BITS
) -> Dict[str, np.ndarray]:
    """Float state dict -> uint64 fixed point (two's complement in
    Z_2^64). int64 intermediates hold scale_bits=16 magnitudes up to
    2^47 exactly — the sample-weighted sums this protocol ships."""
    scale = float(1 << scale_bits)
    return {
        k: np.round(np.asarray(v, np.float64) * scale)
        .astype(np.int64)
        .astype(np.uint64)
        for k, v in state.items()
    }


def dequantize64(
    state: Mapping[str, np.ndarray], scale_bits: int = DEFAULT_SCALE_BITS
) -> Dict[str, np.ndarray]:
    """uint64 ring elements -> float64; values >= 2^63 read as negative."""
    scale = float(1 << scale_bits)
    out = {}
    for k, v in state.items():
        signed = np.asarray(v, np.uint64).astype(np.int64)  # two's complement
        out[k] = signed.astype(np.float64) / scale
    return out

# RFC 3526 group 14: 2048-bit MODP prime, generator 2. A fixed,
# nothing-up-my-sleeve group (pi-derived) — the standard choice for
# finite-field DH without external crypto dependencies.
MODP_P = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A637ED6B0BFF5CB6F406B7ED"
    "EE386BFB5A899FA5AE9F24117C4B1FE649286651ECE45B3DC2007CB8A163BF05"
    "98DA48361C55D39A69163FA8FD24CF5F83655D23DCA3AD961C62F356208552BB"
    "9ED529077096966D670C354E4ABC9804F1746C08CA18217C32905E462E36CE3B"
    "E39E772C180E86039B2783A2EC07A28FB5C55DF06F4C52C9DE2BCBF695581718"
    "3995497CEA956AE515D2261898FA051015728E5A8AACAA68FFFFFFFFFFFFFFFF",
    16,
)
MODP_G = 2
_SK_BITS = 256  # exponent size; 2^256 work factor ≫ the group's ~110-bit strength


def dh_keypair() -> Tuple[int, int]:
    """Fresh per-round DH keypair (sk, pk = g^sk mod p)."""
    sk = secrets.randbits(_SK_BITS) | 1
    return sk, pow(MODP_G, sk, MODP_P)


# Cached DH powers. The 2048-bit modexp is the protocol's dominant host
# cost (measured ~7 ms each; a 64-cohort round needs ~190 per client)
# and depends only on (sk, pk), not the context — so the three
# context-distinct derivations per peer pair (mask seed + one sealed-box
# direction each way) share one cached power. Forward secrecy is why the
# keypairs are per-round, so the cache must not outlive them: parties
# call :func:`purge_dh_secrets` when they discard a round's secure state
# (worker key rotation, manager round finalization/abort) — a plain dict
# with targeted eviction, NOT an lru_cache that would retain old rounds'
# shared secrets for the process lifetime. Guarded by a lock: callers
# run on asyncio worker THREADS (the event-loop starvation fix moved
# all heavy crypto off-loop), so purge's iterate-and-delete can race a
# concurrent insert/clear — "dict changed size during iteration" inside
# a finalize task would leave a round locked forever. The 7 ms modexp
# itself runs OUTSIDE the lock so threads don't serialize on it.
import threading as _threading

_DH_CACHE: Dict[Tuple[int, int], bytes] = {}
# Sized for a 256-member cohort's full pair matrix (256·255 = 65,280
# entries, ~26 MB of 2048-bit powers). The old 16384 cap sat on a knife
# edge: C=128 (16,256 pairs) just fit, while C=256 wholesale-clear()ed
# the cache mid-protocol — every worker's inbox decryption then
# recomputed 255 modexps, the broadcast phase ballooned ~540 s past the
# manager's HTTP timeout, and the whole cohort silently failed to ack.
# Eviction is oldest-first (insertion order), never a wholesale clear.
_DH_CACHE_MAX = 65536
_DH_CACHE_LOCK = _threading.Lock()
# Tombstones for purged secret keys: a ~7 ms modexp in flight on a pool
# thread when its sk is purged would otherwise re-insert the dead
# round's shared secret AFTER the purge, silently undoing it. sks are
# per-round ephemerals and never legitimately reused after purge, so
# refusing future cache inserts for them costs nothing. Insertion-
# ordered with a hard cap — oldest tombstones fall off.
_DH_PURGED: Dict[int, None] = {}
_DH_PURGED_MAX = 4096
# hit/miss tally for the cache — the C=256 postmortem above was, at
# bottom, an *invisible* cache wipe; dh_cache_stats() surfaces the
# cache's health as manager gauges so the next sizing knife edge shows
# up on a dashboard instead of in a timeout
_DH_CACHE_HITS = 0
_DH_CACHE_MISSES = 0


def _dh_raw(sk: int, pk_other: int) -> bytes:
    global _DH_CACHE_HITS, _DH_CACHE_MISSES
    key = (sk, pk_other)
    with _DH_CACHE_LOCK:
        v = _DH_CACHE.get(key)
        if v is None:
            _DH_CACHE_MISSES += 1
        else:
            _DH_CACHE_HITS += 1
    if v is None:
        v = pow(pk_other, sk, MODP_P).to_bytes(256, "big")
        with _DH_CACHE_LOCK:
            if sk not in _DH_PURGED:
                while len(_DH_CACHE) >= _DH_CACHE_MAX:
                    _DH_CACHE.pop(next(iter(_DH_CACHE)))
                _DH_CACHE[key] = v
    return v


def dh_cache_stats() -> Dict[str, int]:
    """Size + hit/miss counters of the process-wide DH power cache,
    read under the cache lock (surfaced as ``dh_cache_*`` gauges by the
    manager's ``/metrics`` endpoint)."""
    with _DH_CACHE_LOCK:
        return {
            "size": len(_DH_CACHE),
            "hits": _DH_CACHE_HITS,
            "misses": _DH_CACHE_MISSES,
        }


def purge_dh_secrets(*sks: int) -> None:
    """Drop every cached DH power derived from the given secret keys.
    Call when a round's secure state is discarded — after this, only a
    party still holding the ephemeral sk itself can rederive the pairwise
    seeds (the forward-secrecy contract of per-round keypairs). Purged
    keys are tombstoned so a concurrent in-flight derivation cannot
    re-insert them."""
    with _DH_CACHE_LOCK:
        for sk in sks:
            _DH_PURGED[sk] = None
        while len(_DH_PURGED) > _DH_PURGED_MAX:
            _DH_PURGED.pop(next(iter(_DH_PURGED)))
        dead = [k for k in _DH_CACHE if k[0] in sks]
        for k in dead:
            del _DH_CACHE[k]


def dh_shared_seed(sk: int, pk_other: int, context: str) -> bytes:
    """32-byte pairwise seed: SHA-256(context ‖ g^(sk_i·sk_j) mod p).

    Symmetric in the pair by DH; ``context`` (the round name) binds masks
    to one round so a replayed upload can't be unmasked with old seeds.
    """
    if not 1 < pk_other < MODP_P - 1:
        raise ValueError("invalid DH public key")
    return hashlib.sha256(
        context.encode() + b"|" + _dh_raw(sk, pk_other)
    ).digest()


# ======================================================================
# Shamir t-of-n secret sharing over GF(2^521 − 1)
#
# The double-masking protocol (Bonawitz et al. 2017) needs each client's
# self-mask seed b_i and mask-DH secret key recoverable by the SERVER
# from any t honest peers — but no fewer. 2^521 − 1 is a Mersenne prime
# comfortably above both 256-bit seeds and 256-bit DH exponents, and
# Python integers make the field arithmetic exact and dependency-free.

SHAMIR_P = (1 << 521) - 1
_SHARE_BYTES = 66  # ceil(521 / 8)


def shamir_share(secret: int, n: int, t: int) -> Dict[int, int]:
    """Split ``secret`` into n shares with threshold t (any t reconstruct,
    t−1 reveal nothing). Returns {x: f(x)} for x = 1..n."""
    if not 0 <= secret < SHAMIR_P:
        raise ValueError("secret out of field range")
    if not 1 <= t <= n:
        raise ValueError(f"need 1 <= t <= n, got t={t}, n={n}")
    coeffs = [secret] + [
        secrets.randbelow(SHAMIR_P) for _ in range(t - 1)
    ]
    out = {}
    for x in range(1, n + 1):
        y = 0
        for c in reversed(coeffs):  # Horner
            y = (y * x + c) % SHAMIR_P
        out[x] = y
    return out


def shamir_reconstruct(shares: Dict[int, int]) -> int:
    """Lagrange interpolation at 0 — exact iff ≥ t shares are supplied
    (fewer yields a uniformly wrong value, by design)."""
    total = 0
    xs = list(shares)
    for xi in xs:
        num, den = 1, 1
        for xj in xs:
            if xj == xi:
                continue
            num = (num * (-xj)) % SHAMIR_P
            den = (den * (xi - xj)) % SHAMIR_P
        total = (
            total + shares[xi] * num * pow(den, SHAMIR_P - 2, SHAMIR_P)
        ) % SHAMIR_P
    return total


def share_to_hex(y: int) -> str:
    return y.to_bytes(_SHARE_BYTES, "big").hex()


def share_from_hex(h: str) -> int:
    return int.from_bytes(bytes.fromhex(h), "big")


# ======================================================================
# authenticated share transport (client→client, relayed via the server)
#
# Share pairs travel through the untrusted manager, so they are
# encrypted+MACed under a key only the two endpoints can derive
# (DH on the dedicated share-transport keypair). Stdlib-only AEAD:
# SHA-256 counter-mode keystream + HMAC-SHA256 (encrypt-then-MAC).

import hmac as _hmac


def _keystream(key: bytes, n: int) -> bytes:
    out = b""
    ctr = 0
    while len(out) < n:
        out += hashlib.sha256(key + b"|ks|" + ctr.to_bytes(8, "big")).digest()
        ctr += 1
    return out[:n]


def seal(key: bytes, plaintext: bytes) -> bytes:
    ct = bytes(
        a ^ b for a, b in zip(plaintext, _keystream(key, len(plaintext)))
    )
    tag = _hmac.new(key, b"|mac|" + ct, hashlib.sha256).digest()
    return tag + ct


def unseal(key: bytes, sealed: bytes) -> bytes:
    """Raises ValueError on a forged/garbled box."""
    tag, ct = sealed[:32], sealed[32:]
    want = _hmac.new(key, b"|mac|" + ct, hashlib.sha256).digest()
    if not _hmac.compare_digest(tag, want):
        raise ValueError("share box failed authentication")
    return bytes(a ^ b for a, b in zip(ct, _keystream(key, len(ct))))


def _pair_sign(my_id: str, other_id: str) -> int:
    """Mask sign convention: the lexicographically-smaller client_id adds
    the pair's mask, the larger subtracts it — identical on every party
    with no coordination."""
    if my_id == other_id:
        raise ValueError("no pairwise mask with self")
    return 1 if my_id < other_id else -1


def pair_mask(seed: bytes, template: Mapping[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Deterministic uniform-uint64 mask per tensor from a 32-byte seed.

    Philox (256-bit key = the seed) is counter-based and bit-identical
    across platforms, so client-side masking and server-side dropout
    recovery derive the same stream from the same seed.
    """
    words = np.frombuffer(seed, dtype=np.uint64)  # 4 × uint64
    gen = np.random.Generator(
        np.random.Philox(
            key=words[:2],  # Philox keys are 128-bit
            counter=np.concatenate([words[2:], np.zeros(2, np.uint64)]),
        )
    )
    # one stream, consumed in sorted-name order: client masking and
    # server recovery must draw identical bits even if their state dicts
    # were built in different insertion orders
    out = {}
    for name in sorted(template):
        out[name] = gen.integers(
            0, 1 << 64, size=np.shape(template[name]), dtype=np.uint64
        )
    return out


def mask_state_dict(
    state: Mapping[str, np.ndarray],
    my_id: str,
    pair_seeds: Mapping[str, bytes],
    scale_bits: int = DEFAULT_SCALE_BITS,
    self_seed: Optional[bytes] = None,
) -> Dict[str, np.ndarray]:
    """Client-side: quantize ``state`` and add every pairwise mask, plus
    (double-masking) the client's own self-mask PRG(b_i).

    ``pair_seeds`` maps each *other* cohort member's client_id to the DH
    seed shared with it. The result is uint64 ring elements — uniform
    noise to anyone missing the seeds. With ``self_seed`` (the Bonawitz
    b_i) the upload stays uniform noise EVEN to a server that somehow
    learned every pairwise seed; b_i is only recoverable from t Shamir
    shares held by the peers.
    """
    out = quantize64(state, scale_bits)
    for other_id, seed in pair_seeds.items():
        sign = _pair_sign(my_id, other_id)
        mask = pair_mask(seed, out)
        for k in out:
            if sign > 0:
                out[k] = (out[k] + mask[k]).astype(np.uint64)
            else:
                out[k] = (out[k] - mask[k]).astype(np.uint64)
    if self_seed is not None:
        mask = pair_mask(self_seed, out)
        for k in out:
            out[k] = (out[k] + mask[k]).astype(np.uint64)
    return out


def self_mask_correction(
    self_seeds: Sequence[bytes], template: Mapping[str, np.ndarray]
) -> Dict[str, np.ndarray]:
    """Server-side: the additive correction removing reporters' self
    masks — the negated sum of PRG(b_i) over the reconstructed b_i."""
    corr = {
        k: np.zeros(np.shape(v), np.uint64) for k, v in template.items()
    }
    for seed in self_seeds:
        mask = pair_mask(seed, template)
        for k in corr:
            corr[k] = (corr[k] - mask[k]).astype(np.uint64)
    return corr


def modular_sum(updates: Sequence[Mapping[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """Σ mod 2^64 over masked uploads (server-side)."""
    total = {k: np.asarray(v, np.uint64).copy() for k, v in updates[0].items()}
    for u in updates[1:]:
        for k in total:
            total[k] = (total[k] + np.asarray(u[k], np.uint64)).astype(np.uint64)
    return total


def dropout_correction(
    dropped_id: str,
    revealed_seeds: Mapping[str, bytes],
    template: Mapping[str, np.ndarray],
) -> Dict[str, np.ndarray]:
    """Server-side: the additive correction cancelling a dropped client.

    Each reporter i's upload contains ``sign(i, d)·mask(seed_id)`` toward
    dropped client d; summing ``sign(d, i)·mask(seed_id)`` over the
    reporters (whose seeds with d they revealed) is exactly the negation
    of the residue.
    """
    corr = {
        k: np.zeros(np.shape(v), np.uint64) for k, v in template.items()
    }
    for reporter_id, seed in revealed_seeds.items():
        sign = _pair_sign(dropped_id, reporter_id)
        mask = pair_mask(seed, template)
        for k in corr:
            if sign > 0:
                corr[k] = (corr[k] + mask[k]).astype(np.uint64)
            else:
                corr[k] = (corr[k] - mask[k]).astype(np.uint64)
    return corr


def unmask_sum(
    masked_sum: Mapping[str, np.ndarray],
    corrections: Sequence[Mapping[str, np.ndarray]],
    scale_bits: int = DEFAULT_SCALE_BITS,
) -> Dict[str, np.ndarray]:
    """Apply dropout corrections and dequantize to float64."""
    total = {k: np.asarray(v, np.uint64).copy() for k, v in masked_sum.items()}
    for corr in corrections:
        for k in total:
            total[k] = (total[k] + np.asarray(corr[k], np.uint64)).astype(np.uint64)
    return dequantize64(total, scale_bits)
