"""Fleet health plane: per-client telemetry ledger + anomaly scoring.

The round pipeline answers *what happened to round N*; this module
answers the operator questions that dominate at fleet scale — *which
client* is slow, *is it getting worse*, and *why was it a straggler*.

:class:`ClientLedger` keeps a bounded ring of per-client per-round
observations (train wall time, upload bytes/bandwidth, reported loss,
heartbeat RTT, participation outcome), persisted crash-safe to
``clients.jsonl`` with the same single-write+flush discipline as
``rounds.jsonl``, and classifies each client from its recent window:

``healthy``
    nothing anomalous in the window.
``slow``
    the client's median train time is a robust (median/MAD) outlier
    against the fleet's per-client medians.
``flaky``
    the client keeps missing rounds it was asked to join, or straggles
    past the reporting window, despite having reported before.
``degrading``
    the client's own train time is trending up — its recent half is
    materially worse than its older half.
``inactive``
    never participated in the window (an edge's own client entry, or a
    client the cohort sampler skipped) — excluded from anomaly gauges.

Classifications are **advisory**: exported as gauges and annotated into
round SLO records (``straggler_why``), never used for eviction. Client
identity is the registration id, so a cold-restarted worker starts a
fresh history; a worker that goes *unavailable* (503s, timeouts) keeps
its id and accumulates the misses that make it ``flaky``.

The scoring helpers (:func:`robust_zscore`, :func:`classify_client`)
are pure functions over observation dicts so the classification edges
(constant history, single sample, step change, flapping) unit-test
without a federation.
"""

from __future__ import annotations

import json
import os
import statistics
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "ClientLedger",
    "classify_client",
    "robust_zscore",
    "STATUSES",
]

#: every classification the ledger can emit, in gauge-export order
STATUSES = ("healthy", "slow", "flaky", "degrading", "inactive")

# -- scoring thresholds (module-level so tests can reference them) ----
#: robust z-score above which a client's median train time is "slow"
SLOW_Z = 3.5
#: minimum clients with train timings before cross-sectional scoring
SLOW_MIN_FLEET = 3
#: missed/straggled rounds in the window before "flaky" fires …
FLAKY_MIN_MISSES = 2
#: … and the minimum fraction of the window they must represent
FLAKY_MIN_FRAC = 0.2
#: recent-half/older-half train-time ratio that means "degrading"
DEGRADE_RATIO = 1.5
#: observations with timings needed before trend detection
DEGRADE_MIN_OBS = 6
#: absolute train-time increase (s) below which trends are noise
DEGRADE_MIN_DELTA_S = 0.01
#: recent/older MFU ratio below which a client is losing compute
#: efficiency (the inverse direction of DEGRADE_RATIO: MFU falls)
MFU_DEGRADE_RATIO = 1.0 / 1.5
#: absolute MFU drop below which MFU trends are noise
MFU_DEGRADE_MIN_DELTA = 0.01
# 1.4826 scales MAD to σ for normal data; the floor keeps an outlier
# detectable when the rest of the fleet is perfectly uniform (MAD = 0)
_MAD_SIGMA = 1.4826
_MAD_FLOOR_FRAC = 0.05
_EPS = 1e-6


def robust_zscore(
    value: float,
    population: Sequence[float],
    *,
    mad_floor_frac: float = _MAD_FLOOR_FRAC,
) -> float:
    """Median/MAD z-score of ``value`` against ``population``.

    The scale floors at ``mad_floor_frac × |median|`` (and an absolute
    epsilon) so a uniform population — MAD exactly zero — still yields
    a finite, large score for a genuine outlier instead of dividing by
    zero, while a value equal to the median scores exactly 0.
    """
    if not population:
        return 0.0
    med = statistics.median(population)
    mad = statistics.median(abs(x - med) for x in population)
    scale = max(_MAD_SIGMA * mad, mad_floor_frac * abs(med), _EPS)
    return (value - med) / scale


def _median(values: Iterable[float]) -> Optional[float]:
    vals = [v for v in values if v is not None]
    return statistics.median(vals) if vals else None


def classify_client(
    window: Sequence[dict],
    fleet_train_medians: Sequence[float],
    *,
    slow_z: float = SLOW_Z,
) -> Tuple[str, str]:
    """Classify one client from its observation ``window`` (oldest
    first) against the fleet's per-client median train times. Returns
    ``(status, reason)``; ``reason`` is the human/SLO-record string.
    """
    if not window:
        return "inactive", "no observations"
    reported = [o for o in window if o.get("outcome") == "reported"]
    missed = [o for o in window if o.get("outcome") in ("missed", "straggler")]
    if not reported and not any(
        o.get("outcome") == "straggler" for o in window
    ):
        return "inactive", "no participation in window"

    # flaky: keeps missing rounds it was asked to join
    n = len(window)
    if (
        len(missed) >= FLAKY_MIN_MISSES
        and len(missed) / n >= FLAKY_MIN_FRAC
    ):
        return "flaky", (
            f"missed or straggled {len(missed)} of last {n} rounds"
        )

    trains = [o["train_s"] for o in reported
              if o.get("train_s") is not None]
    my_med = _median(trains)

    # slow: cross-sectional outlier vs the fleet's per-client medians
    if (
        my_med is not None
        and len(fleet_train_medians) >= SLOW_MIN_FLEET
    ):
        z = robust_zscore(my_med, fleet_train_medians)
        if z >= slow_z:
            fleet_med = statistics.median(fleet_train_medians)
            return "slow", (
                f"train_s median {my_med:.3f}s vs fleet median "
                f"{fleet_med:.3f}s (robust z={z:.1f})"
            )

    # degrading: own train time trending up within the window
    if len(trains) >= DEGRADE_MIN_OBS:
        half = len(trains) // 2
        older, recent = _median(trains[:half]), _median(trains[half:])
        if (
            older is not None and recent is not None
            and recent >= DEGRADE_RATIO * older
            and recent - older >= DEGRADE_MIN_DELTA_S
        ):
            return "degrading", (
                f"train_s median {older:.3f}s -> {recent:.3f}s over "
                f"last {len(trains)} reports"
            )

    # degrading (compute plane): own MFU trending DOWN — a client whose
    # wall time holds steady while its delivered FLOPs collapse (e.g.
    # a recompile storm, thermal throttling) would otherwise pass every
    # wall-clock check above
    mfus = [o["mfu"] for o in reported if o.get("mfu") is not None]
    if len(mfus) >= DEGRADE_MIN_OBS:
        half = len(mfus) // 2
        older, recent = _median(mfus[:half]), _median(mfus[half:])
        if (
            older is not None and recent is not None
            and recent <= MFU_DEGRADE_RATIO * older
            and older - recent >= MFU_DEGRADE_MIN_DELTA
        ):
            return "degrading", (
                f"mfu median {older:.3f} -> {recent:.3f} over "
                f"last {len(mfus)} reports"
            )

    return "healthy", ""


class ClientLedger:
    """Bounded per-client observation ring with crash-safe persistence.

    Thread-safe (ingest folds run off-loop); every mutation happens
    under one lock and every ``clients.jsonl`` append is a single
    ``write()`` + flush, mirroring :class:`baton_tpu.utils.slog
    .RoundsLog` so a crash tears at most the final line.
    """

    def __init__(
        self,
        window: int = 32,
        log_path: Optional[str] = None,
        metrics=None,
        node: str = "manager",
    ) -> None:
        self.window = max(2, int(window))
        self.node = node
        self.metrics = metrics
        self._obs: Dict[str, deque] = {}
        self._lock = threading.Lock()
        self._log_path = log_path
        if log_path:
            os.makedirs(
                os.path.dirname(os.path.abspath(log_path)), exist_ok=True
            )

    # ------------------------------------------------------------------
    def observe(
        self,
        client_id: str,
        round_name: Optional[str],
        outcome: str,
        *,
        train_s: Optional[float] = None,
        upload_bytes: Optional[int] = None,
        upload_s: Optional[float] = None,
        loss: Optional[float] = None,
        hb_rtt_s: Optional[float] = None,
        n_samples: Optional[float] = None,
        via_edge: Optional[str] = None,
        mfu: Optional[float] = None,
        compile_s: Optional[float] = None,
        recompile_storm: Optional[bool] = None,
        ts: Optional[float] = None,
    ) -> dict:
        """Record one per-round observation for ``client_id``."""
        entry = {
            "ts": round(time.time() if ts is None else ts, 6),
            "node": self.node,
            "round": round_name,
            "client": client_id,
            "outcome": outcome,
        }
        if train_s is not None:
            entry["train_s"] = round(float(train_s), 6)
        if upload_bytes is not None:
            entry["upload_bytes"] = int(upload_bytes)
        if upload_s is not None and upload_s > 0:
            entry["upload_s"] = round(float(upload_s), 6)
            if upload_bytes:
                entry["upload_bw_bps"] = round(upload_bytes / upload_s, 1)
        if loss is not None:
            entry["loss"] = float(loss)
        if hb_rtt_s is not None:
            entry["hb_rtt_s"] = round(float(hb_rtt_s), 6)
        if n_samples is not None:
            entry["n_samples"] = float(n_samples)
        if via_edge is not None:
            entry["via_edge"] = via_edge
        if mfu is not None:
            entry["mfu"] = round(float(mfu), 6)
        if compile_s is not None:
            entry["compile_s"] = round(float(compile_s), 6)
        if recompile_storm:
            entry["recompile_storm"] = True
        with self._lock:
            ring = self._obs.get(client_id)
            if ring is None:
                ring = self._obs[client_id] = deque(maxlen=self.window)
            ring.append(entry)
        if self._log_path:
            data = json.dumps(entry, default=repr) + "\n"
            with self._lock:
                with open(self._log_path, "a", encoding="utf-8") as fh:
                    fh.write(data)
                    fh.flush()
        if self.metrics is not None:
            self.metrics.inc("fleet_observations")
        return entry

    def record_round(
        self,
        round_name: Optional[str],
        cohort: Iterable[str],
        participants: Iterable[str],
        responses: Optional[Dict[str, dict]] = None,
    ) -> Dict[str, str]:
        """Fold one finished round into the ledger.

        ``cohort`` is every client the round *asked* (the notify
        fan-out), ``participants`` those that acked ``round_start``,
        ``responses`` the per-client response dicts of those that
        reported (fields like ``timings``/``upload_bytes``/
        ``loss_history`` are picked up when present). Returns the
        *straggler-why* map: a classification-backed reason for every
        cohort member that did not report.
        """
        responses = responses or {}
        participants = set(participants)
        cohort = set(cohort) | participants | set(responses)
        for cid in sorted(cohort):
            resp = responses.get(cid)
            if resp is not None:
                timings = resp.get("timings") or {}
                loss_hist = resp.get("loss_history") or []
                compute = resp.get("compute") or {}
                self.observe(
                    cid, round_name, "reported",
                    train_s=timings.get("train_s"),
                    upload_bytes=resp.get("upload_bytes"),
                    upload_s=timings.get("upload_s"),
                    loss=loss_hist[-1] if loss_hist else None,
                    hb_rtt_s=timings.get("hb_rtt_s"),
                    n_samples=resp.get("n_samples"),
                    via_edge=resp.get("via_edge"),
                    mfu=compute.get("mfu"),
                    compile_s=compute.get("compile_s"),
                    recompile_storm=compute.get("recompile_storm"),
                )
            elif cid in participants:
                self.observe(cid, round_name, "straggler")
            else:
                self.observe(cid, round_name, "missed")
        why: Dict[str, str] = {}
        if cohort - set(responses):
            classified = self.classify_all()
            for cid in sorted(cohort - set(responses)):
                info = classified.get(cid)
                if info is None:
                    continue
                if info["status"] == "inactive":
                    # edges and never-participating registrations carry
                    # no train history; naming them every round would
                    # drown the real stragglers
                    continue
                if info["status"] != "healthy":
                    why[cid] = f"{info['status']}: {info['reason']}"
                else:
                    why[cid] = (
                        f"healthy: first straggle in last "
                        f"{info['rounds_seen']} rounds"
                        if cid in participants
                        else "healthy: did not ack round_start"
                    )
        return why

    # ------------------------------------------------------------------
    def classify_all(self) -> Dict[str, dict]:
        """``{client_id: {"status", "reason", …window stats}}`` for the
        whole ledger, computed from the current windows."""
        with self._lock:
            windows = {cid: list(ring) for cid, ring in self._obs.items()}
        fleet_meds = []
        per_client_med: Dict[str, Optional[float]] = {}
        for cid, win in windows.items():
            med = _median(
                o.get("train_s") for o in win
                if o.get("outcome") == "reported"
            )
            per_client_med[cid] = med
            if med is not None:
                fleet_meds.append(med)
        out: Dict[str, dict] = {}
        for cid, win in windows.items():
            status, reason = classify_client(win, fleet_meds)
            last = win[-1]
            reported = [o for o in win if o.get("outcome") == "reported"]
            info = {
                "status": status,
                "reason": reason,
                "rounds_seen": len(win),
                "reported": len(reported),
                "straggled": sum(
                    o.get("outcome") == "straggler" for o in win
                ),
                "missed": sum(o.get("outcome") == "missed" for o in win),
                # windowed recompile-storm count: the pin_shapes runbook
                # quarantines exactly the clients whose storms triggered
                # the alert, so the offender set must come from the same
                # ledger window the classification does
                "storms": sum(
                    1 for o in win if o.get("recompile_storm")
                ),
                "last_round": last.get("round"),
                "last_outcome": last.get("outcome"),
                "last_ts": last.get("ts"),
            }
            med = per_client_med.get(cid)
            if med is not None:
                info["train_s_median"] = round(med, 6)
            for key in ("train_s", "upload_bytes", "upload_bw_bps",
                        "loss", "hb_rtt_s", "via_edge",
                        "mfu", "compile_s"):
                for o in reversed(reported):
                    if o.get(key) is not None:
                        info[key] = o[key]
                        break
            out[cid] = info
        return out

    def class_counts(self) -> Dict[str, int]:
        counts = {status: 0 for status in STATUSES}
        for info in self.classify_all().values():
            counts[info["status"]] += 1
        return counts

    def export_gauges(self, metrics) -> Dict[str, int]:
        """Publish advisory ``fleet_clients_*`` class counts."""
        counts = self.class_counts()
        metrics.set_gauge("fleet_clients_total",
                          sum(counts.values()))
        for status in STATUSES:
            metrics.set_gauge(f"fleet_clients_{status}", counts[status])
        return counts

    def health_snapshot(self) -> dict:
        """The ``GET /{name}/fleet/health`` payload."""
        clients = self.classify_all()
        counts = {status: 0 for status in STATUSES}
        for info in clients.values():
            counts[info["status"]] += 1
        return {
            "node": self.node,
            "ts": round(time.time(), 6),
            "window": self.window,
            "summary": dict(counts, total=len(clients)),
            "clients": clients,
        }

    def health_slice(self, client_ids=None) -> dict:
        """The forensics-bundle fleet evidence: classifications for the
        implicated clients only (the round's stragglers), or — with no
        ids — every client currently classified non-healthy. Bounded so
        a bundle never embeds a 10k-client ledger dump."""
        clients = self.classify_all()
        if client_ids is not None:
            picked = {cid: clients[cid] for cid in client_ids
                      if cid in clients}
            unknown = sorted(set(client_ids) - set(clients))
        else:
            picked = {cid: info for cid, info in clients.items()
                      if info["status"] != "healthy"}
            unknown = []
        return {
            "node": self.node,
            "window": self.window,
            "implicated": sorted(picked),
            "unknown": unknown,
            "clients": picked,
        }

    # ------------------------------------------------------------------
    def known_clients(self) -> List[str]:
        with self._lock:
            return sorted(self._obs)

    def forget(self, client_id: str) -> None:
        """Drop a client's ring (e.g. on deregistration) — the
        persisted ``clients.jsonl`` history is kept."""
        with self._lock:
            self._obs.pop(client_id, None)
