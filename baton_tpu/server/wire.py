"""Tensor wire format for the control plane.

The reference ships pickled PyTorch state_dicts over HTTP
(manager.py:85,98; worker.py:92,117) — unpickling network bytes on both
sides. SURVEY §2.8 flags this for redesign. The native format here,
``BTW1``, is safetensors-shaped: a JSON header describing dtype/shape/
offset per tensor plus a raw little-endian payload — zero-copy decode,
no code execution on parse.

    b"BTW1" | uint32 header_len (LE) | header JSON | raw tensor bytes

Header: ``{"meta": {...json-safe metadata...},
"tensors": {name: {"dtype": str, "shape": [...], "offset": int}}}``.

Pickle *decode* compatibility with reference workers is retained behind
an explicit ``allow_pickle=True`` opt-in (demo parity only — the demo
protocol is pickle, SURVEY §2.8).
"""

from __future__ import annotations

import json
import pickle
import struct
from typing import Any, Dict, Mapping, Tuple

import numpy as np

MAGIC = b"BTW1"
CONTENT_TYPE = "application/x-baton-tensors"
PICKLE_CONTENT_TYPE = "application/x-pickle"

_ALLOWED_DTYPES = {
    "float16",
    "bfloat16",
    "float32",
    "float64",
    "int8",
    "int16",
    "int32",
    "int64",
    "uint8",
    "uint16",
    "uint32",
    "uint64",
    "bool",
}


def _np_dtype(name: str):
    # whitelist BEFORE np.dtype: an attacker-controlled header string
    # must not reach the dtype constructor (arbitrary names raise
    # TypeError past the 400 path, and exotic dtypes like 'V<n>'/'object'
    # have no business on this wire)
    if name not in _ALLOWED_DTYPES:
        raise ValueError(f"disallowed tensor dtype {name!r}")
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def encode(tensors: Mapping[str, np.ndarray], meta: Dict[str, Any]) -> bytes:
    """Serialize ``{name: array}`` + JSON-safe metadata to BTW1 bytes.

    Exact-size allocation: the header is laid out first, then the
    output buffer is allocated once at its final size and tensor bytes
    are written into it through numpy views — no per-tensor ``tobytes``
    copies, no BytesIO growth doubling, no final concatenation. This
    matters when the manager encodes a round blob of a large model.
    """
    header: Dict[str, Any] = {"meta": meta, "tensors": {}}
    arrs = []
    offset = 0
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        dtype_name = (
            "bfloat16" if arr.dtype.name == "bfloat16" else arr.dtype.name
        )
        if dtype_name not in _ALLOWED_DTYPES:
            raise ValueError(f"unsupported tensor dtype {arr.dtype} for {name!r}")
        header["tensors"][name] = {
            "dtype": dtype_name,
            "shape": list(arr.shape),
            "offset": offset,
        }
        arrs.append(arr)
        offset += arr.nbytes
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    body_start = len(MAGIC) + 4 + len(hdr)
    out = bytearray(body_start + offset)
    out[: len(MAGIC)] = MAGIC
    struct.pack_into("<I", out, len(MAGIC), len(hdr))
    out[len(MAGIC) + 4 : body_start] = hdr
    pos = body_start
    for arr in arrs:
        if arr.nbytes:
            dst = np.frombuffer(out, np.uint8, count=arr.nbytes, offset=pos)
            dst[:] = arr.reshape(-1).view(np.uint8)
        pos += arr.nbytes
    return bytes(out)


def is_btw1(data) -> bool:
    """Cheap magic sniff — admission paths (chunked-upload first frames,
    disk-reloaded outbox slots) reject non-BTW1 bytes before buffering
    or decoding anything."""
    return bytes(data[:4]) == MAGIC


def decode(data: bytes) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Parse BTW1 bytes → (tensors, meta). No code execution.

    Zero-copy: each returned array is an ``np.frombuffer`` view into
    ``data``'s buffer, not a copy — decoding a 100 MB payload allocates
    ~0 additional tensor memory (tests/test_wire.py asserts this). The
    views keep ``data`` alive; callers that need to outlive the request
    body don't need to do anything special, the refcount handles it.

    Contract for attacker-controlled input: any malformed payload —
    truncated, bit-flipped, wrong lengths — raises ``ValueError`` (or a
    ``json``/``Key``/``Index`` error the server's 400 path equally
    catches); never anything that escapes a standard except clause, and
    never interpretation of the bytes as code (fuzzed in
    tests/test_wire.py)."""
    if data[:4] != MAGIC:
        raise ValueError("not a BTW1 payload")
    try:
        (hdr_len,) = struct.unpack("<I", data[4:8])
    except struct.error as e:
        raise ValueError(f"truncated BTW1 header: {e}") from e
    # explicit bounds check: a declared header length past the end of
    # the buffer must fail as "truncated", not as whatever json makes of
    # a silently-short slice
    if 8 + hdr_len > len(data):
        raise ValueError(
            f"truncated BTW1 header: declares {hdr_len} bytes, "
            f"{len(data) - 8} available"
        )
    header = json.loads(data[8 : 8 + hdr_len].decode("utf-8"))
    # explicit structural validation: a crafted VALID-JSON header with
    # wrong types (null tensors, float shapes, string offsets) must hit
    # the same ValueError contract as corrupt bytes, not leak TypeError/
    # AttributeError past it
    if not isinstance(header, dict) or not isinstance(
        header.get("tensors"), dict
    ):
        raise ValueError("BTW1 header is not {tensors: {...}}")
    body = memoryview(data)[8 + hdr_len :]
    tensors: Dict[str, np.ndarray] = {}
    for name, info in header["tensors"].items():
        if not isinstance(info, dict):
            raise ValueError(f"bad tensor entry for {name!r}")
        dtype = _np_dtype(info.get("dtype"))
        shape = info.get("shape")
        offset = info.get("offset")
        # `type(..) is int` on purpose: bool is an int subclass and JSON
        # true/false must not pass as dimensions/offsets
        if (
            not isinstance(shape, list)
            or not all(type(s) is int and s >= 0 for s in shape)
            or type(offset) is not int
            or offset < 0
        ):
            raise ValueError(f"bad shape/offset for {name!r}")
        shape = tuple(shape)
        # size math in unbounded Python ints, bounds-checked against the
        # actual body BEFORE any numpy call — crafted huge dims must not
        # reach C-long conversions (OverflowError escapes the contract)
        count = 1
        for s in shape:
            count *= s
        nbytes = count * dtype.itemsize
        if offset + nbytes > len(body):
            raise ValueError(f"tensor {name!r} extends past the payload")
        arr = np.frombuffer(body[offset : offset + nbytes], dtype=dtype).reshape(shape)
        tensors[name] = arr
    meta = header.get("meta", {})
    if not isinstance(meta, dict):
        raise ValueError("BTW1 meta is not a dict")
    return tensors, meta


def decode_any(
    body: bytes, content_type: str | None = None, allow_pickle: bool = False
) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Decode a round_start/update body: BTW1 natively, pickle only when
    explicitly allowed (reference-demo compatibility)."""
    if body[:4] == MAGIC:
        return decode(body)
    if not allow_pickle:
        raise ValueError(
            "refusing non-BTW1 payload (enable allow_pickle for reference-"
            "protocol compatibility)"
        )
    obj = pickle.loads(body)
    meta = {k: v for k, v in obj.items() if k != "state_dict"}
    tensors = {
        k: _to_numpy(v) for k, v in obj.get("state_dict", {}).items()
    }
    return tensors, meta


def encode_pickle(tensors: Mapping[str, np.ndarray], meta: Dict[str, Any]) -> bytes:
    """Reference-protocol body: pickled {state_dict, **meta} with numpy
    values (torch tensors on the reference side pickle-compatibly map to
    arrays via __array__)."""
    obj = dict(meta)
    obj["state_dict"] = {k: np.asarray(v) for k, v in tensors.items()}
    return pickle.dumps(obj)


def _to_numpy(v) -> np.ndarray:
    if isinstance(v, np.ndarray):
        return v
    # torch.Tensor and friends expose __array__ / .numpy()
    numpy_fn = getattr(v, "numpy", None)
    if callable(numpy_fn):
        try:
            return np.asarray(numpy_fn())
        except (TypeError, RuntimeError):
            pass
    return np.asarray(v)
