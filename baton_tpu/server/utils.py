"""Control-plane utilities.

Replaces reference utils.py with deliberate fixes (SURVEY §2.9 decisions):

* ``random_key`` — cryptographic (``secrets``), any length, with
  replacement. The reference used ``random.sample(ascii_letters, n)``:
  non-crypto, no repeated chars, max length 52 (utils.py:38-39). FIXED.
* ``json_clean`` — same semantics as utils.py:23-35: strips ``key`` and
  ``state_dict`` fields so secrets/bulk tensors never leak into JSON
  introspection responses; stringifies datetimes; tuplifies sets. KEPT.
* ``RunningMean`` — exact weighted mean. The reference's EpochProgress
  running mean is biased (utils.py:85-88: inputs [4,2,6] → 4.75, true
  mean 4.0). FIXED.
* ``PeriodicTask`` — asyncio start/stop sleep-loop wrapper (utils.py:42-67),
  kept for heartbeats/culling, with the first call optionally immediate.
"""

from __future__ import annotations

import asyncio
import secrets
import string
from contextlib import suppress
from datetime import datetime
from typing import Any

_ALPHABET = string.ascii_letters + string.digits


def random_key(length: int = 32) -> str:
    """Cryptographically random URL-safe token of ``length`` chars."""
    return "".join(secrets.choice(_ALPHABET) for _ in range(length))


def json_clean(data: Any) -> Any:
    """Recursively sanitize a structure for JSON responses.

    Drops ``key``/``state_dict`` entries (credentials and bulk tensors),
    stringifies datetimes, tuplifies sets — reference utils.py:23-35
    semantics, extended to lists/tuples.
    """
    if isinstance(data, dict):
        return {
            k: json_clean(v)
            for k, v in data.items()
            if k not in ("key", "state_dict")
        }
    if isinstance(data, (list, tuple)):
        return [json_clean(v) for v in data]
    if isinstance(data, set):
        return [json_clean(v) for v in sorted(data, key=str)]
    if isinstance(data, datetime):
        return str(data)
    return data


class RunningMean:
    """Exact (optionally weighted) running mean."""

    def __init__(self) -> None:
        self.total = 0.0
        self.weight = 0.0

    def update(self, value: float, weight: float = 1.0) -> None:
        self.total += float(value) * float(weight)
        self.weight += float(weight)

    @property
    def mean(self) -> float:
        return self.total / self.weight if self.weight else 0.0


class PeriodicTask:
    """Run an async callable every ``interval`` seconds until stopped."""

    def __init__(self, func, interval: float, run_immediately: bool = False):
        self.func = func
        self.interval = interval
        self.run_immediately = run_immediately
        self.is_started = False
        self._task = None

    def start(self) -> "PeriodicTask":
        if not self.is_started:
            self.is_started = True
            self._task = asyncio.ensure_future(self._run())
        return self

    async def stop(self) -> None:
        if self.is_started:
            self.is_started = False
            self._task.cancel()
            with suppress(asyncio.CancelledError):
                await self._task

    async def _run(self) -> None:
        if self.run_immediately and self.is_started:
            await self.func()
        while self.is_started:
            await asyncio.sleep(self.interval)
            await self.func()
