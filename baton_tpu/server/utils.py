"""Control-plane utilities.

Replaces reference utils.py with deliberate fixes (SURVEY §2.9 decisions):

* ``random_key`` — cryptographic (``secrets``), any length, with
  replacement. The reference used ``random.sample(ascii_letters, n)``:
  non-crypto, no repeated chars, max length 52 (utils.py:38-39). FIXED.
* ``json_clean`` — same semantics as utils.py:23-35: strips ``key`` and
  ``state_dict`` fields so secrets/bulk tensors never leak into JSON
  introspection responses; stringifies datetimes; tuplifies sets. KEPT.
* ``RunningMean`` — exact weighted mean. The reference's EpochProgress
  running mean is biased (utils.py:85-88: inputs [4,2,6] → 4.75, true
  mean 4.0). FIXED.
* ``PeriodicTask`` — periodic scheduling for heartbeats/culling. Same
  *capability* as reference utils.py:42-67, different mechanism: an
  ``asyncio.Event``-gated wait loop (stop is a prompt event set, not a
  task cancellation), optional immediate first tick, and exception
  logging so one failed tick doesn't silently kill the schedule.
"""

from __future__ import annotations

import asyncio
import json
import secrets
import string
from contextlib import suppress
from datetime import datetime
from typing import Any

_ALPHABET = string.ascii_letters + string.digits


def random_key(length: int = 32) -> str:
    """Cryptographically random URL-safe token of ``length`` chars."""
    return "".join(secrets.choice(_ALPHABET) for _ in range(length))


def json_clean(data: Any) -> Any:
    """Recursively sanitize a structure for JSON responses.

    Drops ``key``/``state_dict`` entries (credentials and bulk tensors),
    stringifies datetimes, tuplifies sets — reference utils.py:23-35
    semantics, extended to lists/tuples.
    """
    if isinstance(data, dict):
        return {
            k: json_clean(v)
            for k, v in data.items()
            if k not in ("key", "state_dict")
        }
    if isinstance(data, (list, tuple)):
        return [json_clean(v) for v in data]
    if isinstance(data, set):
        return [json_clean(v) for v in sorted(data, key=str)]
    if isinstance(data, datetime):
        return str(data)
    return data


async def bounded_gather(*coros, limit: int, return_exceptions: bool = False):
    """``asyncio.gather`` behind a concurrency window.

    A 1024-client round must not mean 1024 simultaneous sockets/file
    descriptors out of the manager (Bonawitz et al. 2019 pace their
    fan-out the same way): at most ``limit`` of the given coroutines run
    at once, the rest wait on a semaphore. Results keep input order.

    Failure semantics match ``gather(return_exceptions=True)`` wrapped
    in a re-raise: one failing coroutine never cancels its siblings —
    every coroutine runs to completion, and only then is the first
    exception raised (or, with ``return_exceptions=True``, exceptions
    are returned in place like plain gather).
    """
    if limit <= 0:
        raise ValueError(f"limit must be positive, got {limit}")
    sem = asyncio.Semaphore(limit)

    async def windowed(coro):
        async with sem:
            return await coro

    results = await asyncio.gather(
        *(windowed(c) for c in coros), return_exceptions=True
    )
    if not return_exceptions:
        for r in results:
            if isinstance(r, BaseException):
                raise r
    return results


class BodyTooLarge(Exception):
    """Raised by :func:`read_body_capped` when a request body exceeds
    the configured cap — the handler answers ``413``."""

    def __init__(self, limit: int, seen: int) -> None:
        super().__init__(f"request body exceeds {limit} bytes (saw >= {seen})")
        self.limit = limit
        self.seen = seen


async def read_body_capped(request, limit):
    """Read an aiohttp request body under a byte cap.

    Two layers of enforcement (ISSUE 3 satellite — the old
    ``await request.read()`` buffered whatever the peer sent):

    * declared size — a ``Content-Length`` above ``limit`` is rejected
      at the door, before a single body byte is read;
    * streamed cap — a chunked-transfer (or lying) client is cut off as
      soon as the accumulated bytes pass ``limit``, so the manager never
      buffers more than ``limit + 64KiB``.

    ``limit=None`` means uncapped (legacy behavior, explicit opt-out).
    Raises :class:`BodyTooLarge`; returns ``bytes`` otherwise.
    """
    if limit is None:
        # explicit opt-out: this IS the uncapped path callers chose
        return await request.read()  # batonlint: allow[BTL020]
    limit = int(limit)
    declared = request.content_length
    if declared is not None and declared > limit:
        raise BodyTooLarge(limit, declared)
    buf = bytearray()
    async for chunk in request.content.iter_chunked(1 << 16):
        buf.extend(chunk)
        if len(buf) > limit:
            raise BodyTooLarge(limit, len(buf))
    return bytes(buf)


# Control-plane JSON (register, heartbeat, secure-agg key/share
# exchange) is a few KiB in the worst case; 4 MiB is two orders of
# magnitude of headroom while still bounding a hostile POST.
MAX_JSON_BODY = 4 << 20


async def read_json_capped(request, limit=MAX_JSON_BODY):
    """Parse a JSON request body under a byte cap.

    The ``await request.json()`` convenience buffers the whole body
    before parsing — on control endpoints that is an unbounded
    allocation driven by the peer. This reads through
    :func:`read_body_capped` (Content-Length precheck + streamed
    cut-off) and parses the result, so control handlers get the same
    413 semantics as the upload path. Raises :class:`BodyTooLarge` on
    oversize and ``json.JSONDecodeError``/``UnicodeDecodeError`` on a
    malformed body (callers already answer 400 for those).
    """
    body = await read_body_capped(request, limit)
    return json.loads(body.decode("utf-8"))


class RunningMean:
    """Exact (optionally weighted) running mean."""

    def __init__(self) -> None:
        self.total = 0.0
        self.weight = 0.0

    def update(self, value: float, weight: float = 1.0) -> None:
        self.total += float(value) * float(weight)
        self.weight += float(weight)

    @property
    def mean(self) -> float:
        return self.total / self.weight if self.weight else 0.0


class PeriodicTask:
    """Run an async callable every ``interval`` seconds until stopped.

    Stop is signalled through an :class:`asyncio.Event` rather than task
    cancellation: a tick in progress finishes cleanly, and ``stop()``
    returns as soon as the loop observes the event (at worst one
    ``interval``'s wait, interrupted immediately by the event). A tick
    that raises is logged and the schedule continues — a transient
    heartbeat failure must not kill liveness checking.
    """

    def __init__(self, func, interval: float, run_immediately: bool = False):
        self.func = func
        self.interval = interval
        self.run_immediately = run_immediately
        self._stop = asyncio.Event()
        self._stop.set()  # not running
        self._loop_task: asyncio.Task | None = None

    @property
    def is_started(self) -> bool:
        return not self._stop.is_set()

    def is_current_task(self) -> bool:
        """True when called from inside this schedule's own tick — used
        to avoid await-on-self deadlocks in restart paths."""
        return (
            self._loop_task is not None
            and self._loop_task is asyncio.current_task()
        )

    def start(self) -> "PeriodicTask":
        if self._stop.is_set():
            self._stop = asyncio.Event()
            self._loop_task = asyncio.get_event_loop().create_task(
                self._schedule()
            )
        return self

    async def stop(self) -> None:
        self._stop.set()
        if self._loop_task is not None:
            with suppress(asyncio.CancelledError):
                await self._loop_task
            self._loop_task = None

    async def _tick(self) -> None:
        try:
            await self.func()
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # keep the schedule alive
            print(f"PeriodicTask({getattr(self.func, '__name__', self.func)}): "
                  f"tick failed: {exc!r}")

    async def _schedule(self) -> None:
        # intentional identity capture: if the task is stopped and
        # restarted, self._stop is replaced — THIS schedule must keep
        # honoring its own generation's stop event, not the new one.
        stop = self._stop  # batonlint: allow[BTL003]
        if self.run_immediately and not stop.is_set():
            await self._tick()
        while not stop.is_set():
            # wait_for(stop.wait(), interval): either the interval elapses
            # (TimeoutError -> run a tick) or stop fires (exit promptly)
            try:
                await asyncio.wait_for(stop.wait(), timeout=self.interval)
                return
            except asyncio.TimeoutError:
                await self._tick()
