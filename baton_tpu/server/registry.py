"""Client membership registry — pure, clock-injected, asyncio-free.

Reference counterpart: client_manager.py:14-150 (registration, heartbeat,
TTL culling, auth), minus the transport: HTTP fan-out lives in
:mod:`baton_tpu.server.http_manager`, so this core is unit-testable with
a fake clock.

Parity decisions (SURVEY §2.3, §2.9):
* client_id format KEPT: ``client_{name}_{6 chars}`` (client_manager.py:89);
  keys are 32 chars but now cryptographically random (FIXED, utils.py:38-39).
* Callback URL derivation KEPT: client-supplied ``url`` or
  ``http://{remote}:{port}/{name}/`` (client_manager.py:95-99).
* Per-client state KEPT: key/remote/port/last_heartbeat/url/last_update/
  num_updates (client_manager.py:100-109).
* TTL culling KEPT (client_manager.py:129-137); eviction notifications to
  the round manager are the caller's job (fixing the straggler hang).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional

from baton_tpu.server.utils import json_clean, random_key


class UnknownClient(KeyError):
    pass


class AuthError(Exception):
    pass


@dataclasses.dataclass
class Client:
    client_id: str
    key: str
    remote: Optional[str]
    port: Optional[int]
    url: Optional[str]
    last_heartbeat: float
    registered_at: float
    last_update: Optional[str] = None
    num_updates: int = 0

    def to_json(self) -> dict:
        return json_clean(dataclasses.asdict(self))


class ClientRegistry:
    def __init__(
        self,
        name: str,
        client_ttl: float = 300.0,
        clock: Callable[[], float] = time.time,
        journal=None,
    ):
        self.name = name
        self.client_ttl = client_ttl
        self._clock = clock
        self.journal = journal
        self.clients: Dict[str, Client] = {}

    def _journal(self, event: str, **fields) -> None:
        if self.journal is not None:
            self.journal.append(event, **fields)

    def __len__(self) -> int:
        return len(self.clients)

    def __contains__(self, client_id: str) -> bool:
        return client_id in self.clients

    def __getitem__(self, client_id: str) -> Client:
        try:
            return self.clients[client_id]
        except KeyError:
            raise UnknownClient(client_id) from None

    # ------------------------------------------------------------------
    def register(
        self,
        remote: Optional[str] = None,
        port: Optional[int] = None,
        url: Optional[str] = None,
    ) -> Client:
        client_id = f"client_{self.name}_{random_key(6)}"
        key = random_key(32)
        if not url:
            url = f"http://{remote}:{port}/{self.name}/"
        now = self._clock()
        client = Client(
            client_id=client_id,
            key=key,
            remote=remote,
            port=port,
            url=url,
            last_heartbeat=now,
            registered_at=now,
        )
        # journal before exposing the credential: a crash after the
        # worker learns its key must still find the key on replay
        self._journal(
            "client_registered",
            client_id=client_id, key=key, remote=remote, port=port,
            url=url, registered_at=now,
        )
        self.clients[client_id] = client
        return client

    def restore_client(
        self,
        client_id: str,
        key: str,
        remote: Optional[str] = None,
        port: Optional[int] = None,
        url: Optional[str] = None,
        registered_at: Optional[float] = None,
        num_updates: int = 0,
        last_update: Optional[str] = None,
    ) -> Client:
        """Re-admit a journal-recovered client with its original id and
        auth key. Not journaled (the journal already knows it); the
        heartbeat clock restarts now so recovery downtime doesn't count
        against the TTL."""
        now = self._clock()
        client = Client(
            client_id=client_id,
            key=key,
            remote=remote,
            port=port,
            url=url,
            last_heartbeat=now,
            registered_at=registered_at if registered_at is not None else now,
            last_update=last_update,
            num_updates=int(num_updates or 0),
        )
        self.clients[client_id] = client
        return client

    def heartbeat(self, client_id: str, key: str) -> None:
        self.verify(client_id, key)
        self.clients[client_id].last_heartbeat = self._clock()

    def verify(self, client_id: str, key: str) -> str:
        """Auth check (reference verify_request, client_manager.py:144-150):
        raises UnknownClient / AuthError → HTTP 401 at the edge."""
        if client_id not in self.clients:
            raise UnknownClient(client_id)
        if self.clients[client_id].key != key:
            raise AuthError(client_id)
        return client_id

    def drop(self, client_id: str) -> None:
        if client_id in self.clients:
            self._journal(
                "client_dropped", client_id=client_id, reason="dropped"
            )
        self.clients.pop(client_id, None)

    def cull(self) -> List[str]:
        """Evict clients whose heartbeat is older than the TTL; returns
        evicted ids so the caller can drop them from a running round."""
        now = self._clock()
        stale = [
            cid
            for cid, c in self.clients.items()
            if (now - c.last_heartbeat) > self.client_ttl
        ]
        for cid in stale:
            self._journal("client_dropped", client_id=cid, reason="culled")
            del self.clients[cid]
        return stale

    def record_update(self, client_id: str, round_name: str) -> None:
        c = self.clients.get(client_id)
        if c is not None:
            c.last_update = round_name
            c.num_updates += 1

    def to_json(self) -> list:
        return [c.to_json() for c in self.clients.values()]
