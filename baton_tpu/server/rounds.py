"""Round state machine — pure, clock-injected, asyncio-free.

Reference counterpart: update_manager.py:17-68, where the round state is
literally an ``asyncio.Lock`` (``in_progress == lock.locked()``,
update_manager.py:31-33). Here the state is explicit data, so the machine
is unit-testable without an event loop and cannot leak a lock.

Deliberate fixes over the reference (SURVEY §2.9, keep/fix record):
* item 3 FIXED — aborting a round (e.g. zero clients accepted) resets
  state; the reference left the lock held when zero clients were
  *registered*, 423-ing every later round.
* item 4 FIXED — ``drop_client`` removes a dead client from the running
  round so ``clients_left`` can reach zero, and ``deadline``/``is_expired``
  give rounds a straggler timeout. The reference round hung forever if a
  participant died mid-round.
* Round naming KEPT: ``update_{name}_{:05d}`` (update_manager.py:26).
* Exception hierarchy KEPT: RoundError/RoundInProgress/RoundNotInProgress
  mirror UpdateException/UpdateInProgress/UpdateNotInProgress
  (update_manager.py:5-14).

Durability: when constructed with a ``journal``
(:class:`baton_tpu.server.journal.Journal`), every state transition is
appended to it *before* the in-memory mutation, so a crash at any point
leaves the journal a superset of memory and replay cannot lose an
acknowledged transition. ``client_end`` journals only the response's
scalar envelope (n_samples, update_id) — never the tensors, which are
the checkpoint's job.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Set

from baton_tpu.server.utils import random_key


class RoundError(Exception):
    pass


class RoundInProgress(RoundError):
    pass


class RoundNotInProgress(RoundError):
    pass


class RoundManager:
    def __init__(
        self,
        name: Optional[str] = None,
        round_timeout: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        journal=None,
    ):
        self.name = name or random_key(6)
        self.round_timeout = round_timeout
        self._clock = clock
        self.journal = journal
        self.loss_history: list = []
        self.n_rounds = 0
        self._in_progress = False
        self._reset_state()

    def _reset_state(self) -> None:
        self.round_name = f"update_{self.name}_{self.n_rounds:05d}"
        self.clients: Set[str] = set()
        self.client_responses: Dict[str, Any] = {}
        self.update_ids: Dict[str, str] = {}
        self.round_meta: Optional[dict] = None
        self.started_at: Optional[float] = None
        # per-round deadline override (runbook adaptive_deadline
        # actuation); cleared with the rest of the round state so an
        # actuated deadline never outlives the round it was fit for
        self.deadline_override: Optional[float] = None
        # wall-clock (epoch) round start: the injected monotonic clock
        # is the right base for expiry math but meaningless across
        # processes — trace spans and rounds.jsonl SLO records need a
        # timestamp a recovered manager incarnation can line up with
        self.started_wall: Optional[float] = None

    def _journal(self, event: str, **fields: Any) -> None:
        if self.journal is not None:
            self.journal.append(event, **fields)

    # ------------------------------------------------------------------
    @property
    def in_progress(self) -> bool:
        return self._in_progress

    @property
    def clients_left(self) -> int:
        return len(self.clients) - len(self.client_responses)

    @property
    def effective_timeout(self) -> Optional[float]:
        """The deadline the running round is actually held to: the
        per-round :meth:`set_deadline` override when one was actuated,
        else the static ``round_timeout``."""
        if self.deadline_override is not None:
            return self.deadline_override
        return self.round_timeout

    @property
    def is_expired(self) -> bool:
        """True when the running round has outlived its deadline."""
        timeout = self.effective_timeout
        if not self._in_progress or timeout is None:
            return False
        return self.elapsed > timeout

    def set_deadline(self, seconds: Optional[float]) -> None:
        """Override THIS round's straggler deadline (runbook
        ``adaptive_deadline``). Applies to the running round only —
        ``_reset_state`` clears it on start/abort, so the static
        ``round_timeout`` is restored the moment the actuation stops
        being re-applied. No-op outside a round."""
        if not self._in_progress:
            return
        self.deadline_override = (
            None if seconds is None else max(0.0, float(seconds))
        )

    @property
    def elapsed(self) -> float:
        """Seconds since the running round started (0 outside a round)."""
        if not self._in_progress or self.started_at is None:
            return 0.0
        return self._clock() - self.started_at

    def __len__(self) -> int:
        return len(self.clients) if self._in_progress else 0

    # ------------------------------------------------------------------
    def start_round(self, **round_meta: Any) -> str:
        if self._in_progress:
            raise RoundInProgress(self.round_name)
        self._reset_state()
        self._journal(
            "round_started", round_name=self.round_name, meta=round_meta
        )
        self._in_progress = True
        self.round_meta = round_meta
        self.started_at = self._clock()
        self.started_wall = time.time()
        return self.round_name

    def resume_round(self, round_name: str, **round_meta: Any) -> str:
        """Re-open a journal-recovered in-flight round under its original
        name, so workers still holding trained updates for it can deliver
        them to the restarted manager. Participants re-join via
        :meth:`client_start` as the re-announce is acked, exactly like a
        fresh round."""
        if self._in_progress:
            raise RoundInProgress(self.round_name)
        self._reset_state()
        self._journal(
            "round_started", round_name=round_name, meta=round_meta,
            resumed=True,
        )
        self.round_name = round_name
        self._in_progress = True
        self.round_meta = round_meta
        self.started_at = self._clock()
        self.started_wall = time.time()
        return self.round_name

    def restart_clock(self) -> None:
        """Restart the round-expiry clock at ``now``.

        The straggler timeout is meant to bound the time a participant
        takes to REPORT after being notified — not the manager's own
        round setup. Callers invoke this as the broadcast guard drops,
        so a slow (or fault-injected) broadcast/secure phase does not
        eat into the participants' reporting window and expire a round
        nobody had a fair chance to answer. No-op outside a round.
        """
        if self._in_progress:
            self.started_at = self._clock()

    def client_start(self, client_id: str) -> None:
        if not self._in_progress:
            raise RoundNotInProgress
        if client_id not in self.clients:
            self._journal(
                "round_client_joined",
                round_name=self.round_name, client_id=client_id,
            )
        self.clients.add(client_id)

    def client_end(self, client_id: str, response: Any) -> None:
        if not self._in_progress:
            raise RoundNotInProgress
        if isinstance(response, dict):
            self._journal(
                "update_accepted",
                round_name=self.round_name,
                client_id=client_id,
                update_id=response.get("update_id"),
                n_samples=response.get("n_samples"),
            )
            uid = response.get("update_id")
            if uid:
                self.update_ids[client_id] = uid
        self.client_responses[client_id] = response

    def drop_client(self, client_id: str) -> None:
        """Remove a participant mid-round (culled/evicted client) so the
        round can complete without it.

        A client that already delivered an accepted update is NOT
        dropped: the 200 ack promised the update counts (at-least-once
        contract), and under streaming aggregation the contribution has
        already been folded into the running sum — it cannot be
        retracted. Culling only removes clients the round is still
        *waiting on*."""
        if not self._in_progress:
            return
        if client_id in self.client_responses:
            return
        if client_id in self.clients:
            self._journal(
                "round_client_dropped",
                round_name=self.round_name, client_id=client_id,
            )
        self.clients.discard(client_id)
        self.client_responses.pop(client_id, None)
        self.update_ids.pop(client_id, None)

    def end_round(self) -> Dict[str, Any]:
        """Finish the round, returning ``{client_id: response}`` for all
        clients that reported (possibly partial on timeout)."""
        if not self._in_progress:
            raise RoundNotInProgress
        self._journal(
            "round_ended",
            round_name=self.round_name, n_rounds=self.n_rounds + 1,
        )
        self._in_progress = False
        self.n_rounds += 1
        return self.client_responses

    def restore(self, n_rounds: int, loss_history) -> None:
        """Resume from checkpointed state: set the round counter and loss
        history and recompute the derived round name. The single entry
        point for manager restart-resume — keeps the name/counter
        invariant here instead of in callers."""
        if self._in_progress:
            raise RoundInProgress(self.round_name)
        self.n_rounds = int(n_rounds)
        self.loss_history = list(loss_history)
        self._reset_state()

    def abort_round(self, reason: Optional[str] = None) -> None:
        """Cancel a round without counting it (e.g. no client accepted
        the broadcast — reference manager.py:90-92 path, minus the
        zero-registered-clients lock leak)."""
        if not self._in_progress:
            return
        self._journal(
            "round_aborted", round_name=self.round_name, reason=reason
        )
        self._in_progress = False
        self._reset_state()
