"""Uplink ingest pipeline — the manager's off-loop decode/fold stages.

The v2 data plane (PR 2) made the *downlink* pull-based and cheap, but
every accepted upload was still wire-decoded, validated, top-k
decompressed, and folded into the streaming accumulator synchronously
on the asyncio event loop. One 100 MB upload therefore stalled every
heartbeat, blob Range GET, and other client's ack for the duration of
a few hundred milliseconds of numpy work — the classic "don't do CPU
work on the loop" failure, at the worst possible place (the hot path
that scales with cohort size).

This module gives the manager a two-stage pipeline instead:

* **decode stage** — a bounded :class:`ThreadPoolExecutor` running
  body decode + payload validation (+ buffered-path decompression).
  Admission is a counted semaphore checked *on the loop*:
  :meth:`IngestPipeline.submit_decode` returns ``None`` when
  ``queue_depth`` jobs are already in flight, and the HTTP handler
  answers ``429 Retry-After`` — backpressure the worker outbox's
  retry/backoff already knows how to honor.

* **fold stage** — ``fold_shards`` single-thread lanes. Submissions
  happen on the event loop *after* acceptance bookkeeping, so each
  lane executes folds in acceptance order (FIFO executor queue), and
  the default ``fold_shards=1`` keeps the StreamingMean fold exactly
  as deterministic as the old on-loop code. ``fold_shards>1`` trades
  that for parallel partial accumulators (see
  :class:`~baton_tpu.ops.aggregation.ShardedStreamingMean`) whose
  weighted partial sums merge at ``end_round`` — associative up to
  fp32 reduction order.

The pipeline reports ``ingest_queue_depth`` (gauge), and
``ingest_decode_s`` / ``ingest_fold_s`` (histogram timers with
p50/p95/p99) through the manager's metrics registry. With a ``tracer``
it also records per-stage ``ingest_decode`` / ``ingest_fold`` spans
into the caller's trace: the context is captured *on the loop* at
submit time (executors don't propagate contextvars), so the spans land
under the handler's ``ingest`` span and the exported round trace shows
queue wait vs. execution per upload.

:class:`ChunkSession` is the server half of the chunked resumable
upload protocol (``PUT /{name}/update_chunk/{update_id}`` with
``offset``/``total`` framing): assembly state for one in-flight upload,
owned by the manager's per-experiment session table.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import wait as _futures_wait
from typing import Any, Callable, List, Optional

from baton_tpu.utils import tracing


class IngestPipeline:
    """Bounded off-loop decode pool + ordered fold lanes.

    Executors are created lazily (an experiment that never receives an
    upload spawns no threads) and torn down by :meth:`shutdown` from the
    app's cleanup hook.
    """

    def __init__(
        self,
        workers: int = 4,
        queue_depth: int = 64,
        fold_shards: int = 1,
        metrics=None,
        retry_after_s: float = 1.0,
        tracer=None,
    ) -> None:
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        if fold_shards < 1:
            raise ValueError(f"fold_shards must be >= 1, got {fold_shards}")
        self.workers = int(workers)
        self.queue_depth = int(queue_depth)
        self.retry_after_s = float(retry_after_s)
        self._metrics = metrics
        self._tracer = tracer
        self._lock = threading.Lock()
        self._inflight = 0
        self._decode_pool: Optional[ThreadPoolExecutor] = None
        self._lanes: List[Optional[ThreadPoolExecutor]] = [None] * int(
            fold_shards)

    # ------------------------------------------------------------------
    @property
    def fold_shards(self) -> int:
        return len(self._lanes)

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def _pool(self) -> ThreadPoolExecutor:
        if self._decode_pool is None:
            self._decode_pool = ThreadPoolExecutor(
                max_workers=self.workers, thread_name_prefix="ingest-decode")
        return self._decode_pool

    def _lane(self, shard: int) -> ThreadPoolExecutor:
        i = int(shard) % len(self._lanes)
        if self._lanes[i] is None:
            # max_workers=1 is the ordering guarantee: one lane, FIFO
            self._lanes[i] = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"ingest-fold-{i}")
        return self._lanes[i]

    def _set_depth_gauge(self, depth: int) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge("ingest_queue_depth", float(depth))

    # ------------------------------------------------------------------
    def submit_decode(self, fn: Callable[[], Any]):
        """Admit + run ``fn`` on the decode pool.

        Returns an awaitable for ``fn()``'s result, or ``None`` when
        ``queue_depth`` jobs are already in flight — the caller turns
        that into ``429 Retry-After`` (admission control happens here,
        on the loop, *before* any expensive work).
        """
        with self._lock:
            if self._inflight >= self.queue_depth:
                return None
            self._inflight += 1
            depth = self._inflight
        self._set_depth_gauge(depth)
        # executors don't carry contextvars: snapshot the caller's trace
        # context here, on the loop, for the stage span recorded below
        ctx = tracing.current_context() if self._tracer is not None else None

        def run():
            t0 = time.perf_counter()
            w0 = time.time()
            try:
                return fn()
            finally:
                with self._lock:
                    self._inflight -= 1
                    left = self._inflight
                self._set_depth_gauge(left)
                dt = time.perf_counter() - t0
                if self._metrics is not None:
                    self._metrics.observe("ingest_decode_s", dt)
                if ctx is not None:
                    self._tracer.record_span(
                        "ingest_decode", trace_id=ctx[0], parent_id=ctx[1],
                        start=w0, end=w0 + dt,
                    )

        return asyncio.get_running_loop().run_in_executor(self._pool(), run)

    def run_unbounded(self, fn: Callable[[], Any]):
        """Off-loop without admission accounting — for work that was
        already admitted once (e.g. decompressing a buffered upload
        after its acceptance checks passed)."""
        return asyncio.get_running_loop().run_in_executor(self._pool(), fn)

    def submit_fold(self, shard: int, fn: Callable[[], Any]):
        """Queue ``fn`` on the shard's fold lane and return an awaitable.

        Submission order *from the event loop* is acceptance order, and
        the single-thread lane preserves it — so ``fold_shards=1``
        reproduces the sequential on-loop fold bit-for-bit.
        """

        ctx = tracing.current_context() if self._tracer is not None else None

        def run():
            t0 = time.perf_counter()
            w0 = time.time()
            try:
                return fn()
            finally:
                dt = time.perf_counter() - t0
                if self._metrics is not None:
                    self._metrics.observe("ingest_fold_s", dt)
                if ctx is not None:
                    self._tracer.record_span(
                        "ingest_fold", trace_id=ctx[0], parent_id=ctx[1],
                        start=w0, end=w0 + dt, shard=int(shard),
                    )

        return asyncio.wrap_future(self._lane(shard).submit(run))

    def drain_folds(self, timeout: Optional[float] = 30.0) -> None:
        """Block until every already-queued fold has run.

        ``end_round`` calls this before consuming the accumulator: an
        accepted update's 200 ack promised its fold would land in the
        round mean, and a forced finish (watchdog expiry, explicit
        ``end_round``) must not break that promise. Safe to call from
        the loop — lane jobs are pure numpy and never touch the loop.
        """
        barriers = [
            lane.submit(lambda: None)
            for lane in self._lanes if lane is not None
        ]
        if barriers:
            _futures_wait(barriers, timeout=timeout)

    def shutdown(self) -> None:
        """Tear down the executors (app cleanup). Queued folds finish;
        queued decodes are abandoned (their rounds are over anyway)."""
        if self._decode_pool is not None:
            self._decode_pool.shutdown(wait=False)
            self._decode_pool = None
        for i, lane in enumerate(self._lanes):
            if lane is not None:
                lane.shutdown(wait=True)
                self._lanes[i] = None


@dataclasses.dataclass
class ChunkSession:
    """Server-side assembly state for one chunked resumable upload.

    The committed prefix is :attr:`offset`; a PUT whose ``offset``
    doesn't equal it gets ``409 {"offset": committed}`` and the worker
    resyncs — the manager's committed offset is authoritative. ``busy``
    rejects interleaved PUTs for the same session (a client must send
    chunks sequentially; a retry racing its own zombie connection must
    not corrupt the buffer).

    With a ``spill_dir`` the body lives in a ``<digest>.part`` file
    (plus a ``.meta`` sidecar naming the session) instead of a
    process-memory bytearray: a manager restart rescans the directory
    (:meth:`restore_sessions`) and keeps every committed prefix — the
    worker's next offset probe resumes mid-upload instead of starting
    over — and upload buffering stops being bounded by RAM.
    """

    client_id: str
    update_id: str
    total: int
    buf: bytearray = dataclasses.field(default_factory=bytearray)
    busy: bool = False
    spill_dir: Optional[str] = None
    _spill_size: int = 0

    def __post_init__(self) -> None:
        if self.spill_dir is None:
            return
        os.makedirs(self.spill_dir, exist_ok=True)
        base = self._spill_base(self.spill_dir, self.client_id,
                                self.update_id)
        self._part_path = base + ".part"
        meta_path = base + ".meta"
        if not os.path.exists(meta_path):
            tmp = meta_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({"client_id": self.client_id,
                           "update_id": self.update_id,
                           "total": self.total}, fh)
            os.replace(tmp, meta_path)
        try:
            self._spill_size = os.path.getsize(self._part_path)
        except OSError:
            self._spill_size = 0

    @staticmethod
    def _spill_base(spill_dir: str, client_id: str, update_id: str) -> str:
        digest = hashlib.sha256(
            f"{client_id}\x00{update_id}".encode("utf-8")
        ).hexdigest()[:24]
        return os.path.join(spill_dir, digest)

    @property
    def offset(self) -> int:
        if self.spill_dir is not None:
            return self._spill_size
        return len(self.buf)

    def extend(self, chunk: bytes) -> None:
        if self.spill_dir is None:
            self.buf.extend(chunk)
            return
        with open(self._part_path, "ab") as fh:
            fh.write(chunk)
            fh.flush()
        self._spill_size += len(chunk)

    def payload(self) -> bytes:
        if self.spill_dir is None:
            return bytes(self.buf)
        try:
            with open(self._part_path, "rb") as fh:
                return fh.read()
        except OSError:
            return b""

    def discard(self) -> None:
        """Release the session's storage (no-op for the in-memory
        path — the bytearray dies with the object)."""
        if self.spill_dir is None:
            return
        base = self._spill_base(self.spill_dir, self.client_id,
                                self.update_id)
        for suffix in (".part", ".meta"):
            try:
                os.remove(base + suffix)
            except OSError:
                pass

    @classmethod
    def restore_sessions(cls, spill_dir: str) -> dict:
        """Rebuild the session table from a spill directory after a
        restart: ``{(client_id, update_id): ChunkSession}`` with each
        offset recomputed from its ``.part`` file's size — the file IS
        the committed prefix. Unreadable sidecars are skipped (a crash
        mid-create loses only that one upload's progress)."""
        out: dict = {}
        try:
            names = os.listdir(spill_dir)
        except OSError:
            return out
        for name in sorted(names):
            if not name.endswith(".meta"):
                continue
            try:
                with open(os.path.join(spill_dir, name), "r",
                          encoding="utf-8") as fh:
                    meta = json.load(fh)
                sess = cls(client_id=str(meta["client_id"]),
                           update_id=str(meta["update_id"]),
                           total=int(meta["total"]),
                           spill_dir=spill_dir)
            except (OSError, ValueError, KeyError, TypeError):
                continue
            out[(sess.client_id, sess.update_id)] = sess
        return out
