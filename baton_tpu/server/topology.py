"""Declarative worker→edge assignment for the hierarchical tier.

The topology is a classic consistent-hash ring: every edge aggregator
owns ``replicas`` pseudo-random points on a 2^64 ring (derived from
SHA-256 of ``"{edge_id}#{i}"``), and a worker maps to the first live
edge point clockwise from the hash of its own id. Properties we lean
on:

- **Deterministic.** Assignment is a pure function of (edge ids, live
  set, worker id) — every component (load generator, benchmarks, an
  operator reading a config) computes the same mapping without
  coordination.
- **Minimal disruption.** When an edge dies, only the workers that
  hashed to its points move (to the next live point clockwise); the
  rest of the fleet keeps its edge and its warm blob cache.
- **Degrade, don't stall.** With zero live edges :meth:`assign`
  returns ``None`` — the caller's contract is that ``None`` means
  *direct to root*. A lost tier degrades fan-in, it never wedges a
  round.

No asyncio, no I/O: the liveness flags are plain state owned by
whoever drives the topology (the loadgen engine flips them when it
kills an edge; a production control plane would drive them from
heartbeats).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple


def _ring_hash(key: str) -> int:
    """Stable 64-bit ring position (first 8 bytes of SHA-256)."""
    return int.from_bytes(
        hashlib.sha256(key.encode("utf-8")).digest()[:8], "big"
    )


class EdgeTopology:
    """Consistent-hash assignment of workers to edge aggregators.

    ``edges`` is the full declared set of edge ids (order-insensitive);
    ``replicas`` points per edge trade balance for ring size (128 keeps
    the max/mean cohort skew under ~1.3 for small E).
    """

    def __init__(self, edges: Sequence[str], replicas: int = 128) -> None:
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        ids = list(edges)
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate edge ids: {ids}")
        self.replicas = replicas
        self._dead: set = set()
        # sorted (point, edge_id) ring; bisect on the point column
        ring: List[Tuple[int, str]] = []
        for eid in ids:
            for i in range(replicas):
                ring.append((_ring_hash(f"{eid}#{i}"), eid))
        ring.sort()
        self._ring = ring
        self._points = [p for p, _ in ring]
        self._edges = ids

    @property
    def edges(self) -> List[str]:
        return list(self._edges)

    def live_edges(self) -> List[str]:
        return [e for e in self._edges if e not in self._dead]

    def is_live(self, edge_id: str) -> bool:
        return edge_id in self._edges and edge_id not in self._dead

    def mark_dead(self, edge_id: str) -> None:
        if edge_id not in self._edges:
            raise KeyError(edge_id)
        self._dead.add(edge_id)

    def mark_alive(self, edge_id: str) -> None:
        if edge_id not in self._edges:
            raise KeyError(edge_id)
        self._dead.discard(edge_id)

    def assign(self, worker_id: str) -> Optional[str]:
        """Edge id owning ``worker_id``, or ``None`` when no edge is
        live (callers route direct to root)."""
        if not self._ring or len(self._dead) >= len(self._edges):
            return None
        start = bisect.bisect_right(self._points, _ring_hash(worker_id))
        n = len(self._ring)
        for off in range(n):
            _, eid = self._ring[(start + off) % n]
            if eid not in self._dead:
                return eid
        return None

    def cohorts(self, worker_ids: Sequence[str]) -> Dict[Optional[str], List[str]]:
        """Group ``worker_ids`` by assigned edge (``None`` bucket =
        direct to root)."""
        out: Dict[Optional[str], List[str]] = {}
        for wid in worker_ids:
            out.setdefault(self.assign(wid), []).append(wid)
        return out
