"""Content-addressed blob store — the manager's broadcast data plane.

The reference pushes a full pickled model to every client per round
(reference manager.py:85): ``O(C × model)`` bytes leave the manager in
one burst. Production FL systems invert the direction (Bonawitz et al.
2019, "Towards Federated Learning at Scale"): the notify message is a
tiny envelope and clients *pull* the round payload. This module holds
the pulled side: immutable byte blobs keyed by their SHA-256 digest.

Content addressing buys three properties the push path cannot have:

* **idempotent resume** — a blob never changes under its digest, so an
  interrupted download continues with an HTTP Range request instead of
  restarting, and the client verifies the digest over the assembled
  bytes (integrity comes free);
* **dedup** — a round whose params did not move hashes to the previous
  round's digest, and an anchored worker skips the download entirely;
* **delta negotiation** — a delta blob is just another immutable blob;
  a worker that reconstructs ``anchor + delta`` can re-hash the result
  and KNOW it holds the same bytes a full download would have given it.

The store is deliberately tiny: the manager retains only the current
round's full blob, its delta blob, and the previous full blob (for
stragglers still mid-download when the round rolls), via
:meth:`BlobStore.retain`.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Iterable, Optional, Tuple


def blob_digest(data) -> str:
    """SHA-256 hex digest of a bytes-like object — the blob's address."""
    return hashlib.sha256(data).hexdigest()


class BlobStore:
    """In-memory ``{digest: (bytes, kind)}`` with explicit retention.

    ``kind`` tags a blob for metrics (``"full"`` vs ``"delta"``); it is
    not part of the address.
    """

    def __init__(self) -> None:
        self._blobs: Dict[str, Tuple[bytes, str]] = {}

    def put(self, data: bytes, kind: str = "full") -> str:
        digest = blob_digest(data)
        # first write wins: blobs are immutable by construction, so a
        # re-put of identical bytes is a no-op (and a re-put of
        # different bytes under one digest is impossible)
        self._blobs.setdefault(digest, (bytes(data), kind))
        return digest

    def get(self, digest: str) -> Optional[Tuple[bytes, str]]:
        return self._blobs.get(digest)

    def retain(self, keep: Iterable[Optional[str]]) -> None:
        """Drop every blob whose digest is not in ``keep``."""
        keep_set = {d for d in keep if d}
        for digest in list(self._blobs):
            if digest not in keep_set:
                del self._blobs[digest]

    def __contains__(self, digest: str) -> bool:
        return digest in self._blobs

    def __len__(self) -> int:
        return len(self._blobs)

    @property
    def total_bytes(self) -> int:
        return sum(len(b) for b, _ in self._blobs.values())
