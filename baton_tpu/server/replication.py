"""Control-plane replication: WAL shipping, leases, and root sharding.

The journal (:mod:`baton_tpu.server.journal`) made a manager restart a
pause instead of an amnesia event — but only on the same machine: the
journal file is local, so losing the *host* still loses the registry
and the in-flight round. This module turns that WAL into a replication
channel, the control-plane analogue of the edge tier's data-plane
scale-out:

* **WAL shipping** — :class:`WalShipper` (on the active root) streams
  the journal's bytes to one or more warm standbys over authed HTTP
  (``POST /{name}/wal_segment``); :class:`WalReceiver` (on each
  standby) appends them to its own journal file. Segments are framed
  by ``(generation, offset)``: ``offset`` is the byte position in the
  journal file and ``generation`` counts compactions (compaction
  truncates the file, so offsets are only comparable within one
  generation). A receiver that sees a frame it cannot splice —
  wrong generation, gap, overlap — answers 409 with the position it
  *can* accept; the shipper either resumes from that offset or falls
  back to a **snapshot catch-up** (the full snapshot file + journal
  tail in one segment), so a standby can join or rejoin at any time.
* **Lease-based active/standby** — leadership is an epoch-numbered
  lease journaled by the active (``ha_lease`` events) and therefore
  shipped with everything else. A standby that observes lease expiry
  (plus a grace period) replays its copy of the WAL, bumps the epoch,
  and starts serving. Every shipped segment carries the sender's
  epoch; a receiver (or a promoted ex-standby) refuses any segment
  whose epoch is below its own with **409 stale_epoch** — the fence
  that keeps a zombie active from split-braining a round after its
  lease was taken.
* **Experiment sharding** — :class:`ExperimentTopology` puts root
  replica ids on the same consistent-hash ring the edge tier uses
  (:mod:`baton_tpu.server.topology`) and assigns each experiment name
  to a replica. A replica marked dead hands its experiments to the
  next live replica clockwise, moving nothing else. Workers and edges
  learn a reassignment lazily: their next heartbeat to the wrong
  replica answers **307** with the owner's URL (plus the full topology
  map in the body), exactly the cheap redirect contract HTTP already
  gives us.

The wire format of one segment (JSON body of ``POST wal_segment``)::

    {"epoch": 3, "replica": "root-0", "generation": 2, "offset": 1184,
     "data": "<journal JSONL bytes>",        # may be "" (lease heartbeat)
     "full": false,                          # true => snapshot catch-up
     "snapshot": null,                       # full only: snapshot file text
     "lease": {"epoch": 3, "holder": "root-0", "expires": 171...}}

Responses: ``200 {"generation": g, "offset": o}`` (the position after
splicing), ``409 {"error": "stale_epoch", "epoch": e}`` (fenced), or
``409 {"error": "resync", "generation": g, "offset": o, "need_full":
bool}`` (shipper must rewind or send a full segment). Auth rides the
``X-Baton-Ha-Token`` header — a shared secret between replicas, never
a per-client credential.

Everything here is transport + framing; the *meaning* of the shipped
bytes stays in ``journal.replay``, which is what the standby runs at
promotion time. Secure-aggregation rounds are the one thing replication
deliberately does not save: mask/share state is never journaled (so a
standby cannot unmask), and a failover aborts such rounds with reason
``secure_agg`` — forward secrecy over availability, documented in the
README.
"""

from __future__ import annotations

import asyncio
import bisect
import contextlib
import logging
import os
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import aiohttp

from baton_tpu.server.journal import SNAPSHOT_SUFFIX
from baton_tpu.server.topology import _ring_hash

log = logging.getLogger(__name__)

#: shared-secret header for replica-to-replica calls
HA_TOKEN_HEADER = "X-Baton-Ha-Token"


# ----------------------------------------------------------------------
class ExperimentTopology:
    """Experiment → root-replica assignment on a consistent-hash ring.

    Mirrors :class:`baton_tpu.server.topology.EdgeTopology` (same vnode
    ring, same clockwise skip-dead walk) but hashes *experiment names*
    over *replica ids*: killing a replica reassigns only the arcs it
    owned, so at most ``1/len(replicas)`` of experiments move."""

    def __init__(self, replicas: Iterable[str], replicas_per_node: int = 128):
        self.replicas: List[str] = list(dict.fromkeys(replicas))
        if not self.replicas:
            raise ValueError("ExperimentTopology needs at least one replica")
        self._dead: set = set()
        ring: List[Tuple[int, str]] = []
        for rid in self.replicas:
            for v in range(replicas_per_node):
                ring.append((_ring_hash(f"{rid}#{v}"), rid))
        ring.sort()
        self._ring = ring
        self._points = [p for p, _ in ring]

    def mark_dead(self, replica_id: str) -> None:
        if replica_id in self.replicas:
            self._dead.add(replica_id)

    def mark_alive(self, replica_id: str) -> None:
        self._dead.discard(replica_id)

    def is_live(self, replica_id: str) -> bool:
        return replica_id in self.replicas and replica_id not in self._dead

    def live_replicas(self) -> List[str]:
        return [r for r in self.replicas if r not in self._dead]

    def assign(self, experiment_name: str) -> Optional[str]:
        """The live replica owning ``experiment_name``; None when every
        replica is dead."""
        if len(self._dead) >= len(self.replicas):
            return None
        start = bisect.bisect_right(self._points, _ring_hash(experiment_name))
        n = len(self._ring)
        for step in range(n):
            rid = self._ring[(start + step) % n][1]
            if rid not in self._dead:
                return rid
        return None

    def cohorts(self) -> Dict[str, List[str]]:
        """Live replica id → sorted experiment list is the *caller's*
        join (experiments live app-side); this returns the live set for
        symmetry with EdgeTopology's console helpers."""
        return {rid: [] for rid in self.live_replicas()}


# ----------------------------------------------------------------------
class WalReceiver:
    """Standby-side WAL endpoint: splices shipped segments into the
    local journal file and tracks the active's lease.

    Owns the journal *files* directly (no :class:`Journal` instance —
    a standby must never append its own events until promoted). All
    state is derivable: a restarted standby answers the first segment
    with a resync and the shipper re-ships from a snapshot."""

    def __init__(self, path: str, metrics: Any = None):
        self.path = os.path.abspath(path)
        self.snapshot_path = self.path + SNAPSHOT_SUFFIX
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self.metrics = metrics
        #: generation of the journal bytes on disk (None until the
        #: first full segment lands — nothing splices before that)
        self.generation: Optional[int] = None
        self.offset = 0
        #: highest epoch ever accepted; segments below it are fenced
        self.epoch = 0
        self.lease: Optional[dict] = None
        self.last_applied_wall: Optional[float] = None
        #: set at promotion: every further segment is refused (the old
        #: active is a zombie by definition once we serve)
        self.closed = False

    # -- applying ------------------------------------------------------
    def apply(self, seg: dict) -> Tuple[int, dict]:
        """Splice one shipped segment; returns ``(status, body)`` for
        the HTTP handler. Pure state machine — no awaits — so a
        segment is applied atomically w.r.t. the event loop."""
        try:
            epoch = int(seg.get("epoch", 0))
            gen = int(seg.get("generation", 0))
            off = int(seg.get("offset", 0))
        except (TypeError, ValueError):
            return 400, {"error": "Bad Segment"}
        if self.closed or epoch < self.epoch:
            self._inc("wal_segments_refused_stale")
            return 409, {"error": "stale_epoch", "epoch": self.epoch}
        full = bool(seg.get("full"))
        data = seg.get("data")
        if data is None:
            data = ""
        if not isinstance(data, str):
            return 400, {"error": "Bad Segment"}
        if not full and (self.generation is None or gen != self.generation
                         or off != self.offset):
            self._inc("wal_resyncs")
            return 409, {
                "error": "resync",
                "generation": self.generation,
                "offset": self.offset,
                "need_full": (self.generation is None
                              or gen != self.generation),
            }
        raw = data.encode("utf-8")
        if full:
            snap = seg.get("snapshot")
            if snap is None:
                with contextlib.suppress(OSError):
                    os.remove(self.snapshot_path)
            else:
                tmp = self.snapshot_path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as fh:
                    fh.write(snap)
                    fh.flush()
                    os.fsync(fh.fileno())
                os.replace(tmp, self.snapshot_path)
            with open(self.path, "wb") as fh:
                fh.write(raw)
                fh.flush()
                os.fsync(fh.fileno())
            self.generation = gen
            self.offset = len(raw)
            self._inc("wal_snapshot_catchups")
        elif raw:
            with open(self.path, "ab") as fh:
                fh.write(raw)
                fh.flush()
            self.offset += len(raw)
        self.epoch = max(self.epoch, epoch)
        lease = seg.get("lease")
        if isinstance(lease, dict):
            self.lease = dict(lease)
            with contextlib.suppress(TypeError, ValueError):
                self.epoch = max(self.epoch, int(lease.get("epoch", 0)))
        self.last_applied_wall = time.time()
        self._inc("wal_segments_applied")
        return 200, {"generation": self.generation, "offset": self.offset}

    # -- promotion inputs ----------------------------------------------
    def lease_expired(self, grace_s: float = 0.0,
                      now: Optional[float] = None) -> bool:
        """True once the active's lease has lapsed past the grace
        window. A standby that never heard a lease does NOT consider it
        expired — promoting blind during a cold fleet boot would mint
        two actives."""
        if self.lease is None:
            return False
        if now is None:
            now = time.time()
        try:
            expires = float(self.lease.get("expires", 0.0))
        except (TypeError, ValueError):
            return False
        return now > expires + grace_s

    def lag_s(self, now: Optional[float] = None) -> Optional[float]:
        if self.last_applied_wall is None:
            return None
        return max(0.0, (time.time() if now is None else now)
                   - self.last_applied_wall)

    def status(self) -> dict:
        return {
            "generation": self.generation,
            "applied_offset": self.offset,
            "epoch": self.epoch,
            "lease": self.lease,
            "lag_s": self.lag_s(),
            "closed": self.closed,
        }

    def _inc(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name)


# ----------------------------------------------------------------------
class WalShipper:
    """Active-side WAL pump: per-standby (generation, offset) cursors,
    incremental tail shipping, resync/snapshot catch-up, and the
    stale-epoch fence check.

    Driven by the manager's ``_ha_tick`` — one :meth:`ship_once` per
    tick, no background task of its own, so teardown is the manager's
    existing task teardown."""

    def __init__(self, name: str, journal: Any, standbys: Iterable[str],
                 replica_id: str,
                 session_factory: Callable[[], aiohttp.ClientSession],
                 token: Optional[str] = None, metrics: Any = None,
                 timeout_s: float = 5.0):
        self.name = name
        self.journal = journal
        self.replica_id = replica_id
        self._session_factory = session_factory
        self.token = token
        self.metrics = metrics
        self.timeout_s = timeout_s
        #: per-standby cursor: where the *standby* is, not where we are
        self._targets: Dict[str, dict] = {
            url.rstrip("/"): {"generation": None, "offset": 0,
                              "need_full": True, "fenced": False,
                              "last_ok_wall": None}
            for url in standbys
        }

    # -- segments ------------------------------------------------------
    def _read_tail(self, offset: int) -> str:
        # runs on a worker thread (ship_once routes segment builds
        # through asyncio.to_thread), under journal.io_lock — the lock,
        # not loop-atomicity, is what keeps a compaction from tearing
        # the segment between the cursor read and the file read
        with open(self.journal.path, "rb") as fh:
            fh.seek(offset)
            return fh.read().decode("utf-8")

    def _full_segment(self, epoch: int, lease: Optional[dict]) -> dict:
        snap = None
        if os.path.exists(self.journal.snapshot_path):
            # same frame-consistency contract as _read_tail; snapshots
            # are one compacted state, not history
            with open(self.journal.snapshot_path, "r",
                      encoding="utf-8") as fh:
                snap = fh.read()
        return {
            "epoch": epoch, "replica": self.replica_id,
            "generation": self.journal.generation, "offset": 0,
            "data": self._read_tail(0), "full": True, "snapshot": snap,
            "lease": lease,
        }

    def _tail_segment(self, epoch: int, offset: int,
                      lease: Optional[dict]) -> dict:
        return {
            "epoch": epoch, "replica": self.replica_id,
            "generation": self.journal.generation, "offset": offset,
            "data": self._read_tail(offset), "full": False,
            "snapshot": None, "lease": lease,
        }

    def _build_segment(self, epoch: int, lease: Optional[dict],
                       generation: Any, offset: int,
                       need_full: bool) -> dict:
        """Build one standby's segment on a worker thread.

        ``journal.io_lock`` makes (generation, journal bytes, snapshot)
        one atomic frame: appends and compactions on the loop wait for
        the read, instead of the read blocking the loop.  The full-vs-
        tail decision is re-taken UNDER the lock — a compaction that
        landed after the loop-side cursor read bumps the generation, and
        shipping a tail against the truncated file would feed the
        standby a torn frame."""
        with self.journal.io_lock:
            if need_full or generation != self.journal.generation:
                return self._full_segment(epoch, lease)
            return self._tail_segment(epoch, offset, lease)

    # -- the pump ------------------------------------------------------
    async def ship_once(self, epoch: int,
                        lease: Optional[dict] = None) -> None:
        """Ship whatever each standby is missing (or an empty lease
        heartbeat when it is caught up). Transport failures are counted
        and retried next tick; a stale-epoch refusal fences the target
        permanently — *we* are the zombie."""
        for url, t in self._targets.items():
            if t["fenced"]:
                continue
            # the cursor snapshot crosses an await here, but the build
            # re-validates it against the live generation under
            # journal.io_lock — a mid-flight compaction downgrades this
            # ship to a full segment instead of tearing it
            seg = await asyncio.to_thread(
                self._build_segment, epoch, lease,
                t["generation"], t["offset"], t["need_full"],
            )
            await self._post(url, t, seg)

    async def _post(self, url: str, t: dict, seg: dict) -> None:
        headers = {}
        if self.token:
            headers[HA_TOKEN_HEADER] = self.token
        try:
            session = self._session_factory()
            async with session.post(
                f"{url}/{self.name}/wal_segment", json=seg, headers=headers,
                timeout=aiohttp.ClientTimeout(total=self.timeout_s),
            ) as resp:
                try:
                    body = await resp.json()
                except (aiohttp.ContentTypeError, ValueError):
                    body = {}
                self._on_response(url, t, seg, resp.status, body)
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            self._inc("wal_ship_errors")

    def _on_response(self, url: str, t: dict, seg: dict, status: int,
                     body: dict) -> None:
        if status == 200:
            t["generation"] = seg["generation"]
            t["offset"] = int(body.get("offset",
                                       seg["offset"]
                                       + len(seg["data"].encode("utf-8"))))
            t["need_full"] = False
            t["last_ok_wall"] = time.time()
            self._inc("wal_segments_shipped")
            if seg["data"]:
                self._inc("wal_bytes_shipped", len(seg["data"]))
            if seg.get("full"):
                self._inc("wal_snapshot_catchups_sent")
        elif status == 409 and body.get("error") == "stale_epoch":
            # the standby (or its successor) moved past our epoch: we
            # lost the lease while we weren't looking. Never ship again.
            t["fenced"] = True
            self._inc("wal_ship_fenced")
            log.warning("wal shipper %s: standby %s fenced us "
                        "(their epoch %s)", self.replica_id, url,
                        body.get("epoch"))
        elif status == 409 and body.get("error") == "resync":
            t["need_full"] = bool(body.get("need_full", True))
            if not t["need_full"]:
                t["generation"] = body.get("generation")
                t["offset"] = int(body.get("offset", 0))
            self._inc("wal_resyncs")
        else:
            self._inc("wal_ship_errors")

    # -- introspection -------------------------------------------------
    @property
    def fenced(self) -> bool:
        """True once ANY standby refused us as stale — the strongest
        possible signal that our lease is gone."""
        return any(t["fenced"] for t in self._targets.values())

    def positions(self) -> Dict[str, dict]:
        return {
            url: {"generation": t["generation"], "offset": t["offset"],
                  "need_full": t["need_full"], "fenced": t["fenced"],
                  "last_ok_wall": t["last_ok_wall"]}
            for url, t in self._targets.items()
        }

    def min_shipped_offset(self) -> int:
        """The most lagging standby's acked offset (0 when none acked
        in the current generation) — the replication_wal_shipped_offset
        gauge."""
        offs = [t["offset"] for t in self._targets.values()
                if t["generation"] == self.journal.generation
                and not t["fenced"]]
        return min(offs) if offs else 0

    def _inc(self, name: str, value: float = 1.0) -> None:
        if self.metrics is not None:
            self.metrics.inc(name, value)


# ----------------------------------------------------------------------
def make_lease(epoch: int, holder: str, duration_s: float,
               now: Optional[float] = None) -> dict:
    """One lease record — journaled as the ``ha_lease`` event's fields
    and carried verbatim on every shipped segment."""
    if now is None:
        now = time.time()
    return {"epoch": int(epoch), "holder": holder,
            "expires": round(now + duration_s, 6)}
