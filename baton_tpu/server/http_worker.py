"""HTTP worker runtime — a real (non-simulated) federated client.

Reference counterpart: worker.py:12-127. Same lifecycle — register with
the manager, heartbeat on a period, accept ``round_start`` broadcasts,
train locally, POST the result to ``update`` — with the recorded fixes
(SURVEY §2.9):

* item 5 FIXED — ``round_in_progress`` is actually set/cleared, so the
  409 duplicate-round guard works (it was dead code in the reference).
* item 7 FIXED — training runs via ``asyncio.to_thread`` (and the XLA
  dispatch releases the GIL), so heartbeats keep flowing mid-round; the
  reference blocked its event loop for the whole local run.
* Heartbeat backoff is capped exponential (reference doubled unboundedly,
  worker.py:78 ``# TODO: better backoff``).
* At-least-once uploads: a trained update is parked in a one-slot
  outbox and retried with capped exponential backoff + jitter until the
  manager answers 200 (delivered) or 410 (round dead — abandoned), a
  401 triggering re-registration in between. The reference — and the
  seed before this — dropped the whole round's training on the first
  failed POST. Every upload carries a fresh ``update_id`` so the
  manager dedupes redelivery (a 200 lost in transit must not
  double-count the client's samples in the aggregate).
* Weights travel as BTW1 tensors, not pickles (pickle decode opt-in).
* Pull data plane (v2): ``round_start`` delivers a small JSON envelope
  naming the round blob by sha256 digest; the worker fetches it from
  ``GET /{name}/round_blob/{digest}`` with HTTP Range resume across
  connection drops, or reconstructs it from the previous round's
  anchor plus a delta blob when the manager offers one (full-blob
  fallback on any digest mismatch). Legacy whole-model push bodies are
  still accepted on the same route.
* Mid-training visibility (reference utils.py:70-91 streams tqdm batch
  progress + a running loss): the jitted multi-epoch run reports each
  finished epoch from inside XLA via an ``io_callback`` progress hook
  (core/training.py::LocalTrainer.progress_fn) into a :class:`Metrics`
  registry served live at ``GET /{name}/metrics`` — gauges
  ``train_epoch`` / ``train_epoch_loss`` update *during* the round.

The training itself is the TPU path: a :class:`LocalTrainer` jitted
multi-epoch run — the reference's Python epoch loop (demo.py:29-49)
compiled into one XLA program.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import os
import pathlib
import random
import secrets
import time
import weakref
from typing import Callable, Optional, Tuple

import aiohttp
from aiohttp import web
import jax
import numpy as np

from baton_tpu.core.model import FedModel
from baton_tpu.core.training import LocalTrainer, make_local_trainer
from baton_tpu.obs.compute import ComputeProbe
from baton_tpu.ops.padding import pad_dataset, round_up
from baton_tpu.server import wire
from baton_tpu.server.state import params_to_state_dict, state_dict_to_params
from baton_tpu.server.utils import (
    BodyTooLarge,
    PeriodicTask,
    random_key,
    read_body_capped,
    read_json_capped,
)
from baton_tpu.utils import profiling, tracing
from baton_tpu.utils.metrics import Metrics
from baton_tpu.utils.tracing import trace_headers

GetData = Callable[[], Tuple[dict, int]]
MAX_BACKOFF = 60.0


@dataclasses.dataclass
class _PendingUpdate:
    """One-slot durable outbox entry: the encoded upload for the round
    in flight, kept until the manager acks (200) or declares the round
    dead (410). ``compressed_template`` is the pre-compression delta —
    needed to fold the kept mass back into the error-feedback residual
    if the update is abandoned rather than delivered."""

    round_name: str
    update_id: str
    body: bytes
    compressed_template: Optional[dict] = None
    attempts: int = 0
    # masked (secure-aggregation) bodies are pinned to the direct root
    # route: an edge partial-folding ring elements would break unmasking
    masked: bool = False


def _parse_compress(spec: Optional[str], seed: int = 0):
    """``"topk:<frac>[:q8|q16]"`` -> ErrorFeedbackCompressor, else None.
    ``seed`` decorrelates the stochastic quantizer across workers."""
    if spec is None:
        return None
    from baton_tpu.ops.compression import ErrorFeedbackCompressor

    parts = spec.split(":")
    if parts[0] != "topk" or len(parts) not in (2, 3):
        raise ValueError(
            f"unknown compress spec {spec!r}; expected 'topk:<frac>[:q8|q16]'"
        )
    frac = float(parts[1])
    if not (0.0 < frac <= 1.0):
        # fail at construction: inside the round task this would only
        # surface as a permanent silent straggler
        raise ValueError(f"compress fraction must be in (0, 1], got {frac}")
    bits = None
    if len(parts) == 3:
        if parts[2] not in ("q8", "q16"):
            raise ValueError(f"unknown quantizer {parts[2]!r} in {spec!r}")
        bits = int(parts[2][1:])
    return ErrorFeedbackCompressor(frac=frac, bits=bits, seed=seed)


class ExperimentWorker:
    """Subclass and implement ``get_data() -> (data_dict, n_samples)``
    (reference worker.py:126-127), or pass ``get_data=`` callable."""

    def __init__(
        self,
        app: web.Application,
        model: FedModel,
        manager: str,
        name: Optional[str] = None,
        port: int = 8080,
        heartbeat_time: float = 60.0,
        worker_host: Optional[str] = None,
        trainer: Optional[LocalTrainer] = None,
        get_data: Optional[GetData] = None,
        allow_pickle: bool = False,
        rng_seed: int = 0,
        auto_register: bool = True,
        compress: Optional[str] = None,
        outbox_backoff: Tuple[float, float] = (0.25, 10.0),
        outbox_dir: Optional[str] = None,
        upload_chunk_bytes: Optional[int] = None,
        max_broadcast_bytes: Optional[int] = 1 << 30,
        train_time_scale: float = 1.0,
        edge: Optional[str] = None,
        edge_retry_s: float = 10.0,
        failover: Optional[list] = None,
    ):
        """``compress`` turns on sparse round-delta uploads
        (ops/compression.py): ``"topk:0.05"`` keeps the top 5% of delta
        coordinates per tensor with error feedback across rounds;
        ``"topk:0.05:q8"`` additionally quantizes kept values to int8.
        Ignored for secure rounds (masking needs dense ring elements).

        ``outbox_backoff``: ``(base, cap)`` seconds for the upload retry
        schedule — capped exponential with jitter.

        ``outbox_dir``: persist the one-slot outbox to disk (the encoded
        upload body as a BTW1 file + a meta JSON). A worker that crashes
        between training and delivery reloads the slot on startup and
        delivers the round's work after restart — closing the ROADMAP
        worker-crash gap. The error-feedback compressor residual is NOT
        persisted: after a crash-reload an abandoned update's kept mass
        cannot be folded back (only delayed-delivery is durable).

        ``upload_chunk_bytes``: updates larger than this are delivered
        as offset/total-framed ``PUT update_chunk`` frames with a
        committed-offset probe, so a transfer that dies at 90% resumes
        from the manager's committed prefix on the outbox's next
        attempt instead of re-sending the whole body. ``None`` (the
        default) keeps the single-POST path for every size.

        ``max_broadcast_bytes``: cap on an inline ``round_start`` body
        (the v1 push path; v2 pull rounds carry only a small envelope).
        Oversized broadcasts get a 413 instead of an unbounded buffer.
        ``None`` disables the cap. Default 1 GiB — far above any real
        model push, low enough to bound a misbehaving peer.

        ``edge``: ``"host:port"`` of an edge aggregator
        (server/edge.py) to route control and data traffic through —
        register, heartbeat, blob fetch, plain uploads, span shipping.
        The edge serves round blobs from its local cache and folds the
        cohort's updates into one upstream partial. On any transport
        failure at the edge, the worker marks the route down for
        ``edge_retry_s`` seconds and falls back DIRECT to the root
        (credentials are root credentials either way — the edge only
        proxies registration), so a dead edge degrades fan-in instead
        of stalling rounds. Masked (secure-aggregation) uploads always
        go direct regardless.

        ``failover``: additional root ``"host:port"`` addresses (warm
        standbys / other replicas, server/replication.py). Any direct-
        root transport failure or 503 (a standby refusing to serve)
        rotates to the next address; a heartbeat answered 307 (the
        experiment was resharded to another replica) retargets every
        subsequent call to the redirect's URL. The at-least-once outbox
        then redelivers the parked update to the new active — which
        either reuses the journaled copy (dedup by update_id) or
        ingests this one.

        ``train_time_scale``: simulated device-speed multiplier, >= 1.0.
        After real training finishes, the worker idles inside the
        ``local_train`` span until the round's compute has taken
        ``scale ×`` its measured wall time — a 3.0 worker behaves like
        hardware 3× slower without burning 3× the CPU. Load-generation
        knob (stragglers, heterogeneous fleets); 1.0 = off."""
        self.name = name or getattr(model, "name", "fedmodel")
        self.model = model
        self.metrics = Metrics()
        # last successful heartbeat round-trip, piggybacked on update
        # metadata so the manager's fleet ledger sees link latency
        self._last_hb_rtt: Optional[float] = None
        # span recorder for this worker's half of each round's trace;
        # the label is upgraded to the registered client_id so traces
        # name workers the way the manager's round state does
        self.tracer = tracing.Tracer(
            service=f"worker#{os.urandom(2).hex()}"
        )
        if trainer is None:
            # default trainer gets the per-epoch metrics heartbeat (module
            # docstring). A USER-supplied trainer is kept verbatim: the
            # trainer is a static jit-cache key (LocalTrainer.train,
            # static_argnums=(0,)), so silently replacing it would break
            # shared-trainer cache reuse across workers — call
            # enable_progress_metrics() to opt a custom trainer in.
            self.trainer = self._with_progress_hook(make_local_trainer(model))
        else:
            self.trainer = trainer
        # compute-plane probe (obs/compute.py): one record per round —
        # compile tracking keyed on the trainer's shape signature, MFU
        # when the model family has FLOPs accounting, null-with-reason
        # otherwise. The record rides update meta to the manager.
        self.compute_probe = ComputeProbe(model=model)
        self.app = app
        self.port = port
        self.worker_host = worker_host
        self.manager = manager
        # direct-root route ring: the configured manager first, then the
        # failover replicas; _root_idx rotates on transport failure/503,
        # _root_override (full base URL) is pinned by a 307 redirect
        self._root_urls = [
            f"http://{m}/{self.name}/"
            for m in [manager] + [str(x) for x in (failover or []) if x]
        ]
        self._root_idx = 0
        self._root_override: Optional[str] = None
        self.edge_url = f"http://{edge}/{self.name}/" if edge else None
        self.edge_retry_s = float(edge_retry_s)
        # monotonic deadline until which the edge route is considered
        # down (0.0 = up); flipped by _edge_failed on transport errors
        self._edge_down_until = 0.0
        self.allow_pickle = allow_pickle
        self.compressor = _parse_compress(compress, seed=rng_seed)
        self._round_anchor: Optional[dict] = None
        # v2 pull data plane: the last dense round blob we hold, by
        # digest — advertised implicitly (the manager's envelope names
        # the delta's base digest; we apply it only if it matches)
        self._anchor_sd: Optional[dict] = None
        self._anchor_digest: Optional[str] = None
        if get_data is not None:
            self.get_data = get_data  # type: ignore[assignment]

        self.params = model.init(jax.random.key(rng_seed))
        self.rng = jax.random.key(rng_seed + 1)

        self.client_id: Optional[str] = None
        self.key: Optional[str] = None
        self.n_updates = 0
        self.round_in_progress = False
        self.outbox_backoff = outbox_backoff
        self.outbox_dir = outbox_dir
        if upload_chunk_bytes is not None and upload_chunk_bytes < 1:
            raise ValueError(
                f"upload_chunk_bytes must be >= 1 or None, "
                f"got {upload_chunk_bytes}"
            )
        self.upload_chunk_bytes = upload_chunk_bytes
        if max_broadcast_bytes is not None and max_broadcast_bytes < 1:
            raise ValueError(
                f"max_broadcast_bytes must be >= 1 or None, "
                f"got {max_broadcast_bytes}"
            )
        self.max_broadcast_bytes = max_broadcast_bytes
        if not train_time_scale >= 1.0:
            raise ValueError(
                f"train_time_scale must be >= 1.0 (a simulated device "
                f"cannot outrun the real one), got {train_time_scale}"
            )
        self.train_time_scale = float(train_time_scale)
        self._pending: Optional[_PendingUpdate] = self._load_persisted()
        if self._pending is not None:
            self.metrics.set_gauge("outbox_pending", 1)
            self.metrics.inc("outbox_reloaded_from_disk")
        self._outbox_task: Optional[asyncio.Task] = None
        self._ship_task: Optional[asyncio.Task] = None
        # guards the broadcast handler's await windows (body read, boxed-
        # share decryption in a worker thread): a duplicate round_start
        # arriving mid-handler must 409 exactly like one arriving
        # mid-training, or two training tasks would stack (§2.9 item 5)
        self._broadcast_busy = False
        self.last_update: Optional[str] = None
        self.heartbeat_time = heartbeat_time
        self._heartbeat_task: Optional[PeriodicTask] = None
        self._register_lock = asyncio.Lock()
        self.__session: Optional[aiohttp.ClientSession] = None

        # secure aggregation (server/secure.py, Bonawitz double masking):
        # one state dict per round_name, bounded to the two most recent
        # rounds so a long-lived worker doesn't accumulate key material.
        self._secure: dict = {}
        # (round_name, state) captured at broadcast time; report_update
        # masks with THIS object and refuses to upload if the live
        # registry was re-keyed underneath it (abort/restart TOCTOU) —
        # never silently falls back to an unmasked upload.
        self._broadcast_secure_st: Optional[tuple] = None

        app.router.add_get(f"/{self.name}/metrics", self.handle_metrics)
        app.router.add_post(f"/{self.name}/round_start", self.handle_round_start)
        app.router.add_post(f"/{self.name}/secure_keys", self.handle_secure_keys)
        app.router.add_post(f"/{self.name}/secure_shares", self.handle_secure_shares)
        app.router.add_post(f"/{self.name}/secure_unmask", self.handle_secure_unmask)
        if auto_register:
            app.on_startup.append(self._on_startup)
            app.on_cleanup.append(self._on_cleanup)

    async def _on_startup(self, app=None) -> None:
        asyncio.ensure_future(self.register_with_manager())
        if self._pending is not None and (
            self._outbox_task is None or self._outbox_task.done()
        ):
            # a disk-reloaded outbox slot: deliver the pre-crash round's
            # trained update as soon as registration lands (the drain
            # loop's 401 path re-registers as needed)
            self._outbox_task = asyncio.ensure_future(self._drain_outbox())

    async def _on_cleanup(self, app=None) -> None:
        if self._heartbeat_task is not None:
            await self._heartbeat_task.stop()
        if self._ship_task is not None and not self._ship_task.done():
            self._ship_task.cancel()
            try:
                await self._ship_task
            except asyncio.CancelledError:
                pass
        if self._outbox_task is not None and not self._outbox_task.done():
            self._outbox_task.cancel()
            try:
                await self._outbox_task
            except asyncio.CancelledError:
                pass
        if self.__session is not None:
            await self.__session.close()

    @property
    def _session(self) -> aiohttp.ClientSession:
        if self.__session is None:
            self.__session = aiohttp.ClientSession()
        return self.__session

    # -- hierarchical routing ------------------------------------------
    def _via_edge(self) -> bool:
        """True while control/data traffic should route through the
        configured edge aggregator (configured AND not marked down)."""
        return (
            self.edge_url is not None
            and time.monotonic() >= self._edge_down_until
        )

    @property
    def manager_url(self) -> str:
        """The current upstream base URL: the edge aggregator while that
        route is healthy, the root manager otherwise. Re-evaluated per
        attempt by every caller, so a mid-retry fallback takes effect on
        the very next request."""
        return self.edge_url if self._via_edge() else self.root_url

    def _edge_failed(self) -> None:
        """Mark the edge route down for ``edge_retry_s``: the next
        attempt at any upstream call goes direct to the root (same
        credentials — the edge only proxies registration)."""
        if self.edge_url is None or not self._via_edge():
            return
        self._edge_down_until = time.monotonic() + self.edge_retry_s
        self.metrics.inc("edge_route_fallbacks")

    @property
    def root_url(self) -> str:
        """The current direct-root base URL: a 307-learned owner when
        one is pinned, else the failover ring's current entry."""
        return self._root_override or self._root_urls[self._root_idx]

    def _root_failed(self) -> None:
        """Rotate the direct-root route to the next replica. A 307
        override is dropped first (the owner it named is the thing that
        just failed); with a single configured root this is a no-op and
        the caller's backoff retries the same address."""
        if self._root_override is not None:
            self._root_override = None
        elif len(self._root_urls) > 1:
            self._root_idx = (self._root_idx + 1) % len(self._root_urls)
        else:
            return
        self.metrics.inc("root_failovers")

    def _follow_redirect(self, data) -> bool:
        """Pin the direct-root route to a 307 redirect's owner URL (the
        topology reassignment contract, server/replication.py)."""
        if not isinstance(data, dict):
            return False
        url = data.get("url")
        if not isinstance(url, str) or not url.startswith("http"):
            return False
        self._root_override = url if url.endswith("/") else url + "/"
        self.metrics.inc("root_redirects_followed")
        return True

    # -- membership ----------------------------------------------------
    async def register_with_manager(self) -> None:
        if self._register_lock.locked():
            return  # collision guard (reference ensure_no_collision, per-instance now)
        # holding the lock across the retry loop IS the point: a second
        # register attempt must wait out the whole handshake, not
        # interleave with it
        async with self._register_lock:  # batonlint: allow[BTL002]
            payload = {"url": self.worker_host, "port": self.port}
            backoff = 1.0
            while True:
                # URL per attempt: an edge failure mid-loop falls the
                # next attempt back to the root (direct registration —
                # the root then notifies this worker directly too)
                via_edge = self._via_edge()
                url = self.manager_url + "register"
                try:
                    async with self._session.get(url, json=payload) as resp:
                        if resp.status != 200:
                            # a standby answers 503; anything non-200
                            # here means "not this replica" — rotate the
                            # root ring and retry (KeyError-ing on the
                            # error body would kill registration for
                            # good)
                            raise aiohttp.ClientResponseError(
                                resp.request_info, (), status=resp.status
                            )
                        data = await resp.json()
                        self.client_id = data["client_id"]
                        self.key = data["key"]
                        self.tracer.service = f"worker:{self.client_id}"
                        break
                except aiohttp.ClientError:
                    if via_edge:
                        self._edge_failed()
                    else:
                        self._root_failed()
                    await asyncio.sleep(backoff)
                    backoff = min(backoff * 2, MAX_BACKOFF)
            # (Re)start the heartbeat loop — unless we're being called
            # FROM it (401 -> re-register path): stopping would cancel
            # the current task ("Task cannot await on itself") and kill
            # heartbeating permanently. The running loop just continues.
            hb = self._heartbeat_task
            inside_heartbeat = hb is not None and hb.is_current_task()
            if not inside_heartbeat:
                if hb is not None:
                    await hb.stop()
                self._heartbeat_task = PeriodicTask(
                    self.heartbeat, self.heartbeat_time
                ).start()

    async def heartbeat(self) -> None:
        backoff = 1.0
        redirects = 0
        while True:
            # URL per attempt, not once at the top: a dead edge marked
            # down inside this loop must not pin every retry to it
            via_edge = self._via_edge()
            url = self.manager_url + "heartbeat"
            try:
                # time only the round-trip: the 401 path's re-register
                # (with its own retry backoff) would skew the histogram
                t_hb0 = time.perf_counter()
                with self.metrics.timer("heartbeat_s"):
                    async with self._session.get(
                        url,
                        json={"client_id": self.client_id, "key": self.key},
                        allow_redirects=False,
                    ) as resp:
                        status = resp.status
                        data = None
                        if status == 307:
                            try:
                                data = await resp.json()
                            except (aiohttp.ContentTypeError, ValueError):
                                data = None
                if status == 200:
                    self._last_hb_rtt = time.perf_counter() - t_hb0
                    return
                if status == 401:
                    # manager restarted or culled us: rejoin
                    return await self.register_with_manager()
                if status == 307:
                    # the experiment was resharded: retarget the direct
                    # root route and heartbeat the owner right away
                    # (bounded — a 307 ping-pong falls into the backoff)
                    if self._follow_redirect(data) and redirects < 2:
                        redirects += 1
                        continue
                if status == 503 and not via_edge:
                    # a standby: our active is elsewhere — rotate the
                    # ring, then take the backoff (an un-promoted fleet
                    # answering 503 everywhere must not spin hot)
                    self._root_failed()
            except aiohttp.ClientError:
                if via_edge:
                    self._edge_failed()
                    continue  # retry direct immediately, no backoff
                self._root_failed()
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, MAX_BACKOFF)

    # -- secure aggregation --------------------------------------------
    def _check_manager_auth(self, request: web.Request) -> bool:
        return (
            request.query.get("client_id") == self.client_id
            and request.query.get("key") == self.key
        )

    def _secure_state(self, round_name: str):
        st = self._secure.get(round_name)
        # a pending claim (keys still being generated in the thread
        # pool) is not usable state: shares/unmask against it would
        # KeyError mid-protocol
        return None if st is None or st.get("pending") else st

    async def handle_secure_keys(self, request: web.Request) -> web.Response:
        """Bonawitz round 0 (AdvertiseKeys): generate the round's two DH
        keypairs — ``c`` keys the pairwise masks, ``s`` keys the
        encrypted share transport — and return both public keys."""
        if not self._check_manager_auth(request):
            return web.json_response({"err": "Wrong Client"}, status=404)
        if self.round_in_progress:
            # Mid-round key rotation would orphan the still-running
            # round's masks (aborted rounds REUSE round names — reference
            # naming parity). Refuse; the manager excludes us this round.
            return web.json_response({"err": "Update in Progress"}, status=409)
        if self._broadcast_busy:
            # a round_start broadcast is mid-acceptance: re-keying now
            # would swap self._secure out from under its await windows
            # (the BTL003 TOCTOU) and strand the broadcast on a dead
            # state object. Refuse; a restarting manager retries keys
            # once the broadcast window closes.
            return web.json_response(
                {"err": "Broadcast in Progress"}, status=409
            )
        from baton_tpu.server import secure

        try:
            data = await read_json_capped(request)
        except BodyTooLarge as exc:
            self.metrics.inc("control_rejected_413")
            return web.json_response(
                {"err": "Body Too Large", "limit_bytes": exc.limit},
                status=413,
            )
        round_name = str(data["round"])
        # claim the round slot BEFORE the thread window (loop-atomic):
        # aborted rounds reuse names, so a stale delayed handler must be
        # detectable by state identity — exactly the manager-side
        # finalization rule — or it would overwrite a replacement
        # round's keys and desynchronize the whole cohort's masks
        replaced = self._secure.get(round_name)
        if replaced is not None:
            # re-keying a live name discards the old state in place —
            # the eviction loop below won't see it, so its cached DH
            # powers must be dropped here (forward-secrecy contract)
            secure.purge_dh_secrets(
                *[k for k in (replaced.get("c_sk"), replaced.get("s_sk"))
                  if k is not None])
        st = {"pending": True, "peer_shares": {}, "partition": None}
        self._secure[round_name] = st
        while len(self._secure) > 2:  # keep current + previous round
            old = self._secure.pop(next(iter(self._secure)))
            # forward secrecy: evicting a round's keys must also drop
            # the cached DH powers derived from them (secure.py);
            # a pending claim has no keys yet
            secure.purge_dh_secrets(
                *[k for k in (old.get("c_sk"), old.get("s_sk"))
                  if k is not None])
        # two 2048-bit modexps (~14 ms): off the loop — with C cohort
        # members sharing one process (tests, benchmarks, co-located
        # silos) the serialized key generations alone starve heartbeats
        (c_sk, c_pk), (s_sk, s_pk) = await asyncio.to_thread(
            lambda: (secure.dh_keypair(), secure.dh_keypair()))
        if self._secure.get(round_name) is not st:
            # a replacement round advertised keys while this handler
            # sat in the thread pool: ours are stale — drop them
            secure.purge_dh_secrets(c_sk, s_sk)
            return web.json_response({"err": "Superseded"}, status=409)
        st.update(c_sk=c_sk, c_pk=c_pk, s_sk=s_sk, s_pk=s_pk)
        del st["pending"]
        return web.json_response({"c_pk": f"{c_pk:x}", "s_pk": f"{s_pk:x}"})

    async def handle_secure_shares(self, request: web.Request) -> web.Response:
        """Bonawitz round 1 (ShareKeys): given the cohort's pk directory,
        draw the self-mask seed b, Shamir-share b and the mask secret key
        c_sk across the cohort, and return each peer's share pair sealed
        under the pairwise share-transport key (the manager relays the
        boxes but cannot open them)."""
        if not self._check_manager_auth(request):
            return web.json_response({"err": "Wrong Client"}, status=404)
        from baton_tpu.server import secure

        try:
            data = await read_json_capped(request)
        except BodyTooLarge as exc:
            self.metrics.inc("control_rejected_413")
            return web.json_response(
                {"err": "Body Too Large", "limit_bytes": exc.limit},
                status=413,
            )
        round_name = str(data["round"])
        st = self._secure_state(round_name)
        if st is None:
            return web.json_response({"err": "Unknown Round"}, status=410)
        try:
            pks = {
                cid: (int(p["c"], 16), int(p["s"], 16))
                for cid, p in data["pks"].items()
            }
            t = int(data["t"])
        except (KeyError, ValueError, TypeError):
            return web.json_response({"err": "Bad Payload"}, status=400)
        cohort = sorted(pks)
        if self.client_id not in cohort or not 1 <= t <= len(cohort):
            return web.json_response({"err": "Bad Cohort"}, status=400)
        if t < len(cohort) // 2 + 1:
            # a low threshold is the t=1 unmask-everyone attack: with
            # t=1 the server holds a reconstructing share of every b_i
            # and c_sk_i by itself — refuse anything below honest majority
            return web.json_response({"err": "Threshold Too Low"}, status=400)
        index = {cid: x + 1 for x, cid in enumerate(cohort)}

        # O(C) 2048-bit modexps (~7 ms each — the protocol's dominant
        # host cost) plus the Shamir splits and box sealing: run the
        # whole block off the event loop. At C=128 this block is ~1 s;
        # serialized across a co-located cohort it starved heartbeats
        # and uploads for minutes (26 unplanned dropouts in the r4
        # secure_round_scale run).
        def _build_boxes():
            b_seed = secrets.token_bytes(32)
            b_shares = secure.shamir_share(
                int.from_bytes(b_seed, "big"), len(cohort), t
            )
            csk_shares = secure.shamir_share(st["c_sk"], len(cohort), t)
            boxes = {}
            for cid in cohort:
                if cid == self.client_id:
                    continue
                # direction-bound key: without the sender->recipient
                # context the pair's two boxes would share one
                # nonce-free keystream (a two-time pad to the relaying
                # server) and a reflected box would still authenticate
                try:
                    key = secure.dh_shared_seed(
                        st["s_sk"], pks[cid][1],
                        f"{round_name}|shares|{self.client_id}>{cid}",
                    )
                except ValueError:
                    continue  # Byzantine pk: skip this peer, not the round
                plain = (
                    secure.share_to_hex(b_shares[index[cid]])
                    + secure.share_to_hex(csk_shares[index[cid]])
                ).encode()
                boxes[cid] = secure.seal(key, plain).hex()
            return b_seed, b_shares, csk_shares, boxes

        b_seed, b_shares, csk_shares, boxes = await asyncio.to_thread(
            _build_boxes)
        if self._secure_state(round_name) is not st:
            # the round was re-keyed (same name — aborted rounds reuse
            # names) while the boxes were being built: these shares are
            # bound to dead keys and must not clobber the new state
            return web.json_response({"err": "Superseded"}, status=409)
        st.update(
            pks=pks, cohort=cohort, index=index, t=t, b=b_seed,
            own_shares=(
                b_shares[index[self.client_id]],
                csk_shares[index[self.client_id]],
            ),
        )
        return web.json_response({"shares": boxes})

    async def handle_secure_unmask(self, request: web.Request) -> web.Response:
        """Bonawitz round 3 (Unmasking): given the server's survivor/
        dropped partition of the masking cohort, return — per peer —
        EITHER its self-mask share (survivors) OR its mask-key share
        (dropped), never both. The either-or rule plus partition pinning
        is what makes a fabricated dropout claim useless: naming a live
        reporter 'dropped' forfeits its self-mask share, so its upload
        stays masked by PRG(b)."""
        if not self._check_manager_auth(request):
            return web.json_response({"err": "Wrong Client"}, status=404)
        from baton_tpu.server import secure

        try:
            data = await read_json_capped(request)
        except BodyTooLarge as exc:
            self.metrics.inc("control_rejected_413")
            return web.json_response(
                {"err": "Body Too Large", "limit_bytes": exc.limit},
                status=413,
            )
        round_name = str(data.get("round", ""))
        st = self._secure_state(round_name)
        if st is None or "cohort" not in st:
            return web.json_response({"err": "Unknown Round"}, status=410)
        try:
            req_c_pk = int(str(data.get("c_pk", "")), 16)
        except ValueError:
            req_c_pk = None
        if req_c_pk != st["c_pk"]:
            # the request is bound to a different key-generation
            # instance of this round NAME (aborted rounds reuse names):
            # a stale finalizer must not pin its partition onto the
            # replacement round's state
            return web.json_response({"err": "Unknown Round"}, status=410)
        survivors = sorted(map(str, data.get("survivors", [])))
        dropped = sorted(map(str, data.get("dropped", [])))
        cohort = set(st["cohort"])
        part = (tuple(survivors), tuple(dropped))
        if (
            not set(survivors) <= cohort
            or not set(dropped) <= cohort
            or set(survivors) & set(dropped)
            or self.client_id not in survivors
            or len(survivors) < st["t"]
        ):
            # len(survivors) >= t also bounds fake-dropout claims: a
            # partition dropping more than n-t members cannot
            # reconstruct and is refused outright
            return web.json_response({"err": "Bad Partition"}, status=400)
        if st["partition"] is not None and st["partition"] != part:
            # a second, DIFFERENT partition for the same round is the
            # both-share-types extraction attack — refuse permanently
            return web.json_response({"err": "Partition Pinned"}, status=409)
        st["partition"] = part

        b_shares = {}
        csk_shares = {}
        for cid in survivors:
            if cid == self.client_id:
                b_shares[cid] = secure.share_to_hex(st["own_shares"][0])
            elif cid in st["peer_shares"]:
                b_shares[cid] = secure.share_to_hex(
                    st["peer_shares"][cid][0]
                )
        for cid in dropped:
            if cid in st["peer_shares"]:
                csk_shares[cid] = secure.share_to_hex(
                    st["peer_shares"][cid][1]
                )
        return web.json_response({
            "x": st["index"][self.client_id],
            "b_shares": b_shares,
            "csk_shares": csk_shares,
        })

    # -- rounds --------------------------------------------------------
    async def handle_round_start(self, request: web.Request) -> web.Response:
        if self.round_in_progress or self._broadcast_busy:
            return web.json_response({"err": "Update in Progress"}, status=409)
        if (
            request.query.get("client_id") != self.client_id
            or request.query.get("key") != self.key
        ):
            asyncio.ensure_future(self.register_with_manager())
            return web.json_response({"err": "Wrong Client"}, status=404)
        self._broadcast_busy = True
        # join the manager's trace: the notify span's traceparent makes
        # this broadcast's fetch/reconstruct spans (and, via the context
        # copied into the spawned round task, the train span) children
        # of the manager's notify
        ctx = tracing.parse_traceparent(request.headers.get("traceparent"))
        token = tracing.activate(ctx[0], ctx[1]) if ctx is not None else None
        try:
            return await self._handle_round_start_locked(request)
        finally:
            if token is not None:
                tracing.deactivate(token)
            self._broadcast_busy = False

    async def _handle_round_start_locked(
        self, request: web.Request
    ) -> web.Response:
        try:
            body = await read_body_capped(request, self.max_broadcast_bytes)
        except BodyTooLarge as exc:
            # mirror the manager's upload-cap contract: reject with the
            # limit in the body so the peer can see what it tripped
            self.metrics.inc("broadcast_rejected_413")
            return web.json_response(
                {"err": "Body Too Large", "limit_bytes": exc.limit},
                status=413,
            )
        if request.content_type == "application/json" or body[:1] == b"{":
            # v2 pull protocol: the notify body is a small JSON envelope;
            # the round payload is fetched from the manager's blob store
            return await self._handle_round_start_envelope(body)
        # legacy push protocol: the full round payload IS the body
        try:
            content_type = request.content_type

            def _decode_broadcast():
                # CPU-bound decode (pickle/BTW1, possibly dequantize) of
                # a model-sized body, off-loop like the manager's and
                # edge's ingest decoders — heartbeats keep flowing while
                # a multi-MB broadcast unpacks
                tensors, meta = wire.decode_any(
                    body, content_type, allow_pickle=self.allow_pickle
                )
                if meta.get("quantized"):
                    # downlink-compressed broadcast (manager
                    # broadcast_quantize_bits): reconstruct dense weights
                    from baton_tpu.ops.compression import dequantize_state_dict

                    tensors = dequantize_state_dict(tensors)
                return tensors, meta

            tensors, meta = await asyncio.to_thread(_decode_broadcast)
            round_name = meta["update_name"]
            n_epoch = int(meta["n_epoch"])
            new_params = state_dict_to_params(self.params, tensors)
        except Exception:
            # reject before mutating any state: a bad broadcast must not
            # leave the worker with half-loaded params
            return web.json_response({"err": "Bad Payload"}, status=400)
        return await self._accept_broadcast(
            round_name, n_epoch, new_params, meta.get("secure")
        )

    async def _handle_round_start_envelope(self, body: bytes) -> web.Response:
        """v2 notify: parse the envelope, obtain the round tensors (anchor
        reuse → delta reconstruction → full blob, in fallback order),
        then accept like any broadcast."""
        try:
            env = json.loads(body.decode("utf-8"))
            round_name = str(env["update_name"])
            n_epoch = int(env["n_epoch"])
            digest = str(env["blob"]["digest"])
            size = int(env["blob"]["size"])
            encoding = env.get("encoding") or {}
            delta_info = env.get("delta")
            delta_chain = env.get("delta_chain")
        except Exception:
            return web.json_response({"err": "Bad Envelope"}, status=400)
        tensors = await self._obtain_round_tensors(
            digest, size, delta_info, delta_chain=delta_chain
        )
        if tensors is None:
            # the manager's bounded notify fan-out naturally backpressures
            # these downloads; a 503 here lets it count the miss and
            # exclude us this round instead of hanging the broadcast
            return web.json_response({"err": "Blob Unavailable"}, status=503)
        try:
            load = tensors
            if encoding.get("quantized"):
                from baton_tpu.ops.compression import dequantize_state_dict

                load = dequantize_state_dict(tensors)
            new_params = state_dict_to_params(self.params, load)
        except Exception:
            return web.json_response({"err": "Bad Payload"}, status=400)
        if not encoding:
            # dense blobs anchor the next round's delta; encoded blobs
            # (@q layouts) are not valid delta bases
            self._anchor_sd = tensors
            self._anchor_digest = digest
        else:
            self._anchor_sd = None
            self._anchor_digest = None
        return await self._accept_broadcast(
            round_name, n_epoch, new_params, env.get("secure")
        )

    async def _obtain_round_tensors(
        self, digest: str, size: int, delta_info, delta_chain=None
    ) -> Optional[dict]:
        """The pull side of the data plane, cheapest source first:

        1. digest matches the anchor we already hold → no download;
        2. the envelope offers a delta FROM our anchor → fetch the small
           delta blob, reconstruct ``anchor + delta``, and verify the
           reconstruction re-encodes to the round blob's digest;
        3. the envelope offers a delta CHAIN passing through our anchor
           (we missed up to ``delta_chain_depth - 1`` rounds) → apply
           the hops from our anchor forward, digest-verifying each
           intermediate reconstruction;
        4. otherwise (fresh worker, stale anchor, or verification
           failure) → fetch the full blob (Range-resumable).
        """
        if self._anchor_sd is not None and self._anchor_digest == digest:
            self.metrics.inc("blob_reused_anchor")
            return dict(self._anchor_sd)
        if (
            delta_info
            and self._anchor_sd is not None
            and delta_info.get("from") == self._anchor_digest
        ):
            try:
                ddigest = str(delta_info["digest"])
                dsize = int(delta_info["size"])
            except (KeyError, TypeError, ValueError):
                ddigest = None
            draw = (
                await self._fetch_blob(ddigest, dsize)
                if ddigest is not None
                else None
            )
            if draw is not None:
                from baton_tpu.ops.compression import apply_delta_state_dict

                try:
                    delta_tensors, _ = wire.decode(draw)
                    cand = apply_delta_state_dict(
                        self._anchor_sd, delta_tensors
                    )
                    if (
                        hashlib.sha256(wire.encode(cand, {})).hexdigest()
                        == digest
                    ):
                        self.metrics.inc("blob_fetch_delta")
                        return cand
                except Exception:
                    pass
                # reconstruction didn't hash to the round blob (anchor
                # drift, corrupt delta): fall through to the full blob
                self.metrics.inc("blob_delta_digest_mismatch")
        if (
            isinstance(delta_chain, list)
            and delta_chain
            and self._anchor_sd is not None
        ):
            # the chain is the manager's recent-hop history (oldest
            # first, up to delta_chain_depth hops): a worker absent k
            # rounds joins at whichever hop starts FROM the anchor it
            # still holds and applies the suffix from there
            start = next(
                (
                    i
                    for i, hop in enumerate(delta_chain)
                    if isinstance(hop, dict)
                    and hop.get("from") == self._anchor_digest
                ),
                None,
            )
            if start is not None:
                cand = await self._apply_delta_chain(
                    delta_chain[start:], digest
                )
                if cand is not None:
                    return cand
        raw = await self._fetch_blob(digest, size)
        if raw is None:
            self.metrics.inc("blob_fetch_failed")
            return None
        try:
            tensors, _ = wire.decode(raw)
        except Exception:
            self.metrics.inc("blob_fetch_failed")
            return None
        self.metrics.inc("blob_fetch_full")
        return tensors

    async def _apply_delta_chain(
        self, hops, final_digest: str
    ) -> Optional[dict]:
        """Walk a depth-N delta chain from our anchor: fetch each hop's
        delta blob, reconstruct, and verify the intermediate state
        re-encodes to the hop's ``to`` digest — every step is as
        bit-defined as the single-hop delta path. Any failure returns
        None and the caller falls back to the full blob."""
        from baton_tpu.ops.compression import apply_delta_state_dict

        # safe across the fetch awaits: each hop re-encodes and verifies
        # against the hop's `to` digest, so a stale anchor cannot produce
        # a wrong state — it fails verification and we fall back to the
        # full blob.
        sd = self._anchor_sd  # batonlint: allow[BTL003]
        to = None
        for i, hop in enumerate(hops):
            try:
                ddigest = str(hop["digest"])
                dsize = int(hop["size"])
                to = str(
                    hop["to"] if hop.get("to") is not None
                    else (final_digest if i == len(hops) - 1 else "")
                )
            except (KeyError, TypeError, ValueError):
                self.metrics.inc("blob_delta_digest_mismatch")
                return None
            raw = await self._fetch_blob(ddigest, dsize)
            if raw is None:
                self.metrics.inc("blob_delta_digest_mismatch")
                return None
            try:
                delta_tensors, _ = wire.decode(raw)
                cand = apply_delta_state_dict(sd, delta_tensors)
                if hashlib.sha256(wire.encode(cand, {})).hexdigest() != to:
                    raise ValueError("hop digest mismatch")
            except Exception:
                self.metrics.inc("blob_delta_digest_mismatch")
                return None
            sd = cand
        if to != final_digest:
            # chain ends at some other state (stale envelope): unusable
            self.metrics.inc("blob_delta_digest_mismatch")
            return None
        self.metrics.inc("blob_fetch_delta_chain")
        return sd

    async def _fetch_blob(
        self, digest: str, size: int, max_attempts: int = 6
    ) -> Optional[bytes]:
        """GET a content-addressed blob, resuming interrupted transfers
        with HTTP Range and verifying the assembled bytes by digest."""
        buf = bytearray()
        base, cap = 0.2, 2.0
        with self.tracer.span(
            "fetch_blob", digest=digest[:12], size=size
        ) as fetch_sp:
            for attempt in range(max_attempts):
                # URL per attempt: the blob is immutable and addressed
                # by digest, so a resume that fell back from a dead edge
                # to the root continues byte-for-byte where it stopped
                via_edge = self._via_edge()
                url = (
                    self.manager_url
                    + f"round_blob/{digest}"
                    + f"?client_id={self.client_id}&key={self.key}"
                )
                headers = trace_headers()
                if buf:
                    # the blob is immutable under its digest, so a partial
                    # body resumes where it stopped instead of restarting
                    headers["Range"] = f"bytes={len(buf)}-"
                    self.metrics.inc("blob_range_resumes")
                try:
                    async with self._session.get(
                        url, headers=headers
                    ) as resp:
                        if resp.status == 200 and buf:
                            buf.clear()  # server ignored the Range: restart
                        if resp.status in (200, 206):
                            async for chunk in resp.content.iter_chunked(
                                1 << 16
                            ):
                                buf.extend(chunk)
                                if len(buf) > size:
                                    # a server streaming MORE than the
                                    # envelope's declared size can never
                                    # verify — stop buffering it now
                                    # instead of after an unbounded read
                                    break
                        elif resp.status in (404, 410):
                            # blob gone (round rolled): give up
                            fetch_sp.set(outcome="gone")
                            return None
                        else:
                            buf.clear()  # 416/401/5xx: restart clean
                except (aiohttp.ClientError, asyncio.TimeoutError):
                    # partial body stays in buf; next attempt resumes
                    if via_edge:
                        self._edge_failed()
                if len(buf) == size:
                    if hashlib.sha256(buf).hexdigest() == digest:
                        fetch_sp.set(attempts=attempt + 1)
                        return bytes(buf)
                    buf.clear()  # corrupt assembly: restart from scratch
                elif len(buf) > size:
                    buf.clear()
                if attempt < max_attempts - 1:
                    delay = min(base * (2 ** attempt), cap)
                    await asyncio.sleep(delay * (0.5 + random.random() / 2))
            fetch_sp.set(outcome="exhausted")
            return None

    async def _accept_broadcast(
        self, round_name: str, n_epoch: int, new_params, secure_info
    ) -> web.Response:
        """Common tail for both broadcast protocols: open the secure
        inbox if the round is masked, load params, and spawn the round."""
        if secure_info is not None:
            st = self._secure.get(round_name)
            if st is None or "cohort" not in st:
                # key agreement / share distribution never happened for
                # this round: we cannot produce a correctly-masked
                # upload, and an unmasked one would poison the sum
                return web.json_response({"err": "No Round Keys"}, status=400)
            mask_cohort = sorted(map(str, secure_info["cohort"]))
            if (
                not set(mask_cohort) <= set(st["cohort"])
                or self.client_id not in mask_cohort
            ):
                return web.json_response({"err": "Bad Cohort"}, status=400)
            opened = await asyncio.to_thread(
                self._decrypt_share_inbox, st, round_name,
                dict(secure_info.get("inbox", {})),
            )
            if self._secure.get(round_name) is not st:
                # the round was re-keyed while the inbox decrypted in
                # the thread pool (an abort/restart REUSES the name):
                # committing mask_cohort into the dead state object
                # would leave the live one bare and let report_update
                # fall through to an UNMASKED upload — the secure-agg
                # downgrade. Refuse the whole broadcast instead.
                self.metrics.inc("broadcast_rejected_superseded")
                return web.json_response({"err": "Superseded"}, status=409)
            st["mask_cohort"] = mask_cohort
            st["scale_bits"] = int(secure_info.get("scale_bits", 16))
            st["peer_shares"].update(opened)
        # capture the secure state AT BROADCAST TIME: report_update
        # must refuse (not downgrade to plain) if this exact object is
        # no longer the round's live state when the upload is built
        self._broadcast_secure_st = (
            (round_name, st) if secure_info is not None else None
        )
        self.params = new_params
        # the broadcast is this round's delta anchor: the manager holds
        # the identical tensors until end_round, so `anchor + delta`
        # reconstructs exactly server-side (ops/compression.py docstring)
        if self.compressor is not None:
            self._round_anchor = {
                k: np.asarray(v, np.float32)
                for k, v in params_to_state_dict(new_params).items()
            }
        if self._pending is not None:
            # an accepted broadcast supersedes any undelivered previous
            # update — including a manager-resumed round re-announcing
            # the SAME name: we retrain from the fresh broadcast, and
            # letting the stale body race the new one could count this
            # worker twice in the resumed round
            self._cancel_pending("superseded")
        self.last_update = round_name
        self.round_in_progress = True
        asyncio.ensure_future(self._run_round(round_name, n_epoch))
        return web.json_response("OK")

    def _decrypt_share_inbox(self, st, round_name: str, inbox: dict) -> dict:
        """Decrypt the share boxes relayed via the manager (Bonawitz
        round 2 inbox); a box failing authentication just leaves that
        sender's shares missing (reconstruction needs only t of n).
        O(C) modexps — call via ``asyncio.to_thread``, same starvation
        argument as handle_secure_shares."""
        from baton_tpu.server import secure as _secure

        opened = {}
        for sender, ct_hex in inbox.items():
            if sender == self.client_id or sender not in st["pks"]:
                continue
            try:
                key = _secure.dh_shared_seed(
                    st["s_sk"], st["pks"][sender][1],
                    f"{round_name}|shares|{sender}>{self.client_id}",
                )
                plain = _secure.unseal(key, bytes.fromhex(ct_hex)).decode()
                half = len(plain) // 2
                opened[sender] = (
                    _secure.share_from_hex(plain[:half]),
                    _secure.share_from_hex(plain[half:]),
                )
            except (ValueError, UnicodeDecodeError):
                pass
        return opened

    def _with_progress_hook(self, trainer: LocalTrainer) -> LocalTrainer:
        """Attach this worker's per-epoch metrics hook to ``trainer``.

        The hook holds the worker only weakly: the jit cache keeps a
        strong reference to the trainer (static argnum) for the process
        lifetime, and a strongly-captured ``self`` would pin the worker
        — params, dataset closure and all — long after app cleanup.
        """
        wref = weakref.ref(self)

        def hook(epoch_idx, epoch_loss):
            w = wref()
            if w is not None:
                # late-bound attribute lookup keeps the hook patchable
                w._on_epoch_progress(epoch_idx, epoch_loss)

        return dataclasses.replace(trainer, progress_fn=hook)

    def enable_progress_metrics(self) -> None:
        """Opt a user-supplied trainer into the per-epoch metrics
        heartbeat. Note this makes the trainer unique to this worker —
        one jit compile per worker instead of shared-trainer reuse."""
        if self.trainer.progress_fn is None:
            self.trainer = self._with_progress_hook(self.trainer)

    def _on_epoch_progress(self, epoch_idx, epoch_loss) -> None:
        """io_callback target: runs on the host after each jitted epoch."""
        self.metrics.set_gauge("train_epoch", int(epoch_idx) + 1)
        self.metrics.set_gauge("train_epoch_loss", float(epoch_loss))
        self.metrics.inc("train_epochs_completed")

    async def handle_metrics(self, request: web.Request) -> web.Response:
        return web.json_response(self.metrics.snapshot())

    def _record_compute(
        self,
        train_sig: tuple,
        train_s: float,
        n_samples: int,
        n_epoch: int,
        steps: int,
        t_wall0: float,
    ) -> Optional[dict]:
        """Build this round's compute record (obs/compute.py) and publish
        it locally: a ``compute`` child span under the active
        ``local_train`` span, the ``compute_compile_s`` histogram with a
        trace exemplar, and latest-round gauges. Returns the record for
        the update meta (None only if the probe itself fails — the round
        must never die on telemetry)."""
        try:
            compute = self.compute_probe.record_round(
                key="local_train",
                signature=train_sig,
                train_s=train_s,
                n_samples=n_samples,
                n_epochs=n_epoch,
                steps=steps,
            )
        except Exception:
            return None
        ctx = tracing.current_context()
        if ctx is not None:
            self.tracer.record_span(
                "compute", ctx[0], t_wall0, time.time(),
                parent_id=ctx[1],
                **{k: v for k, v in compute.items() if v is not None},
            )
        compile_s = compute.get("compile_s")
        if isinstance(compile_s, (int, float)):
            self.metrics.observe(
                "compute_compile_s", float(compile_s), exemplar=ctx
            )
        if not compute.get("cache_hit") and compute.get("recompiles"):
            self.metrics.inc("compute_recompiles")
        for gauge, key in (
            ("compute_mfu", "mfu"),
            ("compute_samples_per_sec_per_chip", "samples_per_sec_per_chip"),
            ("compute_peak_hbm_gb", "peak_hbm_gb"),
            ("compute_steps", "steps"),
        ):
            val = compute.get(key)
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                self.metrics.set_gauge(gauge, float(val))
        self.metrics.set_gauge(
            "compute_recompile_storm",
            1.0 if compute.get("recompile_storm") else 0.0,
        )
        return compute

    async def _run_round(self, round_name: str, n_epoch: int) -> None:
        # reset per-round progress so round N+1's zero-epochs state is
        # distinguishable from round N's completion
        self.metrics.set_gauge("train_epoch", 0)
        self.metrics.set_gauge("train_epoch_loss", 0.0)
        try:
            data, n_samples = self.get_data()
            self.rng, sub = jax.random.split(self.rng)

            def train():
                capacity = round_up(
                    next(iter(data.values())).shape[0], self.trainer.batch_size
                )
                padded, n = pad_dataset(
                    {k: np.asarray(v) for k, v in data.items()}, capacity
                )
                assert n == n_samples or n_samples <= n
                try:
                    sig = self.trainer.train_signature(padded, n_epoch)
                    steps = self.trainer.steps_per_round(capacity, n_epoch)
                except Exception:
                    # delegating trainer wrappers (chaos harnesses proxy
                    # only ``train``) need not expose the accounting
                    # helpers — derive the shape signature locally;
                    # build_record defaults steps epoch-wise
                    sig = (
                        tuple(sorted(
                            (k, tuple(v.shape), str(v.dtype))
                            for k, v in padded.items()
                        )),
                        int(n_epoch),
                    )
                    steps = None
                # forensics: when a capture:true alert armed a one-shot
                # profiler capture, this step consumes it (no-op when
                # unarmed; jax.profiler failures are swallowed inside)
                with profiling.forensics_trace():
                    params, _, losses = self.trainer.train(
                        self.params, padded, np.int32(n_samples), sub,
                        n_epoch
                    )
                return params, np.asarray(losses), sig, steps

            # explicit derived trace id: under a live traceparent
            # context (copied into this task at ensure_future) the span
            # parents to the manager's notify; on a legacy broadcast
            # with no context it still joins the round's derived trace
            trace_id = tracing.make_trace_id(self.name, round_name)
            with self.tracer.span(
                "local_train", trace_id=trace_id, round=round_name,
                n_epoch=n_epoch, n_samples=n_samples,
            ) as train_sp:
                loop = asyncio.get_running_loop()
                t_train0 = loop.time()
                t_wall0 = time.time()
                params, loss_history, train_sig, steps = (
                    await asyncio.to_thread(train)
                )
                if self.train_time_scale > 1.0:
                    # pad to scale× the measured compute time: simulated
                    # slow hardware, same numerics (see __init__ doc)
                    extra = (self.train_time_scale - 1.0) * (
                        loop.time() - t_train0
                    )
                    train_sp.set(time_scale=self.train_time_scale)
                    await asyncio.sleep(extra)
                train_s = loop.time() - t_train0
                if len(loss_history):
                    train_sp.set(final_loss=float(loss_history[-1]))
                # observed inside the span so the histogram exemplar
                # carries this round's local_train span context
                self.metrics.observe(
                    "local_train_s", train_s,
                    exemplar=tracing.current_context(),
                )
                compute = self._record_compute(
                    train_sig, train_s, n_samples, n_epoch, steps, t_wall0
                )
            self.params = params
            await self.report_update(
                round_name, n_samples, loss_history,
                timings={
                    "train_s": train_s,
                    "hb_rtt_s": self._last_hb_rtt,
                },
                compute=compute,
            )
        finally:
            self.round_in_progress = False

    async def report_update(
        self, round_name: str, n_samples: int, loss_history,
        timings: Optional[dict] = None,
        compute: Optional[dict] = None,
    ) -> None:
        """Encode the trained update and park it in the outbox; actual
        delivery (with retries) happens in :meth:`_drain_outbox`. Returns
        as soon as the slot is filled, so the caller's round bookkeeping
        never waits on the network. ``timings`` (self-reported seconds,
        e.g. ``{"train_s": …, "hb_rtt_s": …}``) ride along in the update
        metadata for the manager's fleet ledger — advisory data, so None
        entries are simply dropped rather than sent. ``compute`` is the
        round's compute record (obs/compute.py) — shipped verbatim
        (nulls INCLUDED: each carries its reason field; the manager's
        sanitizer enforces that invariant server-side). The meta dict is
        shared by every encode branch and the chunked upload slices the
        same body, so both plain and chunked paths carry it."""
        update_id = random_key(16)
        meta = {
            "update_name": round_name,
            "n_samples": int(n_samples),
            "loss_history": [float(x) for x in loss_history],
            "update_id": update_id,
        }
        if timings:
            cleaned = {
                k: round(float(v), 6)
                for k, v in timings.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            }
            if cleaned:
                meta["timings"] = cleaned
        if compute:
            meta["compute"] = compute
        # use the secure state captured AT BROADCAST TIME, not a fresh
        # registry fetch: if the round was re-keyed since (abort/restart
        # reusing the name mid-round), a fresh fetch returns the NEW
        # round's bare state, "mask_cohort" is absent, and the upload
        # silently falls through to the PLAIN branch — defeating secure
        # aggregation. Refuse instead; the manager treats us as a
        # dropout and Shamir-recovers our masks.
        captured = self._broadcast_secure_st
        st = None
        if captured is not None and captured[0] == round_name:
            st = captured[1]
            if self._secure.get(round_name) is not st or "mask_cohort" not in st:
                self.metrics.inc("updates_refused_secure_downgrade")
                self._broadcast_secure_st = None
                return
        compressed_payload = None  # set only on the compressed branch
        if st is not None:
            # Secure round: upload sample-weighted quantized params plus
            # every pairwise mask and the self mask PRG(b) — the manager
            # can only use the cohort sum (server/secure.py). Weighting
            # happens client-side because the server cannot scale a
            # masked ring element.
            from baton_tpu.server import secure

            # O(C) seed modexps + O(C) Philox masks over the full state
            # dict — by far the heaviest per-upload host work in a
            # secure round. Off the loop (same starvation argument as
            # handle_secure_shares); numpy mask generation also releases
            # the GIL, so co-located cohorts overlap it.
            def _build_masked_body():
                seeds = {
                    other: secure.dh_shared_seed(
                        st["c_sk"], st["pks"][other][0], round_name
                    )
                    for other in st["mask_cohort"]
                    if other != self.client_id
                }
                weighted = {
                    k: np.asarray(v, np.float64) * float(n_samples)
                    for k, v in params_to_state_dict(self.params).items()
                }
                return wire.encode(
                    secure.mask_state_dict(
                        weighted, self.client_id, seeds, st["scale_bits"],
                        self_seed=st["b"],
                    ),
                    dict(meta, secure=True, scale_bits=st["scale_bits"]),
                )

            body = await asyncio.to_thread(_build_masked_body)
        elif self.compressor is not None and self._round_anchor is not None:
            # sparse round delta (ops/compression.py): top-k of
            # (trained - broadcast) with error feedback; flat wire layout
            # "<name>@idx"/"<name>@val" (+"@scale" when quantized)
            sd = params_to_state_dict(self.params)
            delta = {
                k: np.asarray(v, np.float32) - self._round_anchor[k]
                for k, v in sd.items()
            }
            compressed_payload = self.compressor.compress(delta)
            compressed_template = delta
            tensors = {}
            for k, p in compressed_payload.items():
                tensors[f"{k}@idx"] = np.asarray(p["idx"], np.int32)
                val = p["val"]
                if isinstance(val, dict):  # quantized {"q", "scale"}
                    tensors[f"{k}@val"] = np.asarray(val["q"])
                    tensors[f"{k}@scale"] = np.asarray(
                        [float(val["scale"])], np.float32
                    )
                else:
                    tensors[f"{k}@val"] = np.asarray(val, np.float32)
            body = wire.encode(
                tensors, dict(meta, compressed={"scheme": "topk"})
            )
        else:
            body = wire.encode(params_to_state_dict(self.params), meta)
        await self._enqueue_update(
            _PendingUpdate(
                round_name=round_name,
                update_id=update_id,
                body=body,
                compressed_template=(
                    compressed_template
                    if compressed_payload is not None
                    else None
                ),
                masked=st is not None,
            )
        )

    # -- at-least-once outbox ------------------------------------------
    def _outbox_paths(self) -> Tuple[pathlib.Path, pathlib.Path]:
        d = pathlib.Path(self.outbox_dir)
        return d / "outbox.body", d / "outbox.json"

    def _persist_pending(self, p: _PendingUpdate) -> None:
        """Write the outbox slot to disk: body first, then the meta JSON
        via tmp-file + ``os.replace`` — the meta rename is the commit
        point, so a crash mid-write leaves either a complete slot or no
        slot, never a half one."""
        if self.outbox_dir is None:
            return
        body_path, meta_path = self._outbox_paths()
        body_path.parent.mkdir(parents=True, exist_ok=True)
        tmp = body_path.with_suffix(".body.tmp")
        tmp.write_bytes(p.body)
        os.replace(tmp, body_path)
        meta = {
            "round_name": p.round_name,
            "update_id": p.update_id,
            "body_len": len(p.body),
        }
        tmp = meta_path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(meta))
        os.replace(tmp, meta_path)

    def _clear_persisted(self) -> None:
        if self.outbox_dir is None:
            return
        for path in self._outbox_paths():
            try:
                path.unlink()
            except FileNotFoundError:
                pass

    def _load_persisted(self) -> Optional[_PendingUpdate]:
        """Reload a crash-survived outbox slot, if the on-disk pair is
        complete and consistent (meta committed, body the advertised
        length, BTW1 magic intact). Anything short of that is treated as
        no slot — delivery is at-least-once, never garbage."""
        if self.outbox_dir is None:
            return None
        body_path, meta_path = self._outbox_paths()
        try:
            meta = json.loads(meta_path.read_text())
            body = body_path.read_bytes()
        except (FileNotFoundError, ValueError, OSError):
            return None
        if (
            not isinstance(meta, dict)
            or len(body) != meta.get("body_len")
            or not wire.is_btw1(body)
        ):
            return None
        try:
            return _PendingUpdate(
                round_name=str(meta["round_name"]),
                update_id=str(meta["update_id"]),
                body=body,
            )
        except KeyError:
            return None

    async def _enqueue_update(self, pending: _PendingUpdate) -> None:
        # one slot: a newer round's update supersedes anything still
        # undelivered (the manager 410s stale rounds anyway).
        # Slot mutation stays loop-atomic (before the first await); only
        # the disk write goes to the thread pool — the outbox body is
        # the full encoded update, large enough that a synchronous
        # write_bytes would stall heartbeats (BTL001).
        if self._pending is not None:
            self._cancel_pending("superseded")
        self._pending = pending
        await asyncio.to_thread(self._persist_pending, pending)
        self.metrics.set_gauge("outbox_pending", 1)
        if self._outbox_task is None or self._outbox_task.done():
            self._outbox_task = asyncio.ensure_future(self._drain_outbox())

    def _cancel_pending(self, reason: str) -> None:
        p, self._pending = self._pending, None
        self._clear_persisted()
        self.metrics.set_gauge("outbox_pending", 0)
        if p is not None and p.compressed_template is not None:
            # the kept mass never reached the manager: fold it back into
            # the error-feedback residual or it is lost for good
            self.compressor.restore(p.compressed_template)
        if p is not None:
            self.metrics.inc(f"updates_abandoned_{reason}")

    async def _drain_outbox(self) -> None:
        """Retry the parked upload until the manager answers 200
        (delivered) or 410 (round dead): capped exponential backoff with
        jitter, re-registering on 401 so the retry after a manager
        restart carries fresh credentials. A 429's ``Retry-After`` is a
        floor under the backoff — the manager's admission control is
        authoritative about when to come back."""
        base, cap = self.outbox_backoff
        while (p := self._pending) is not None:
            status, retry_after = await self._post_update(p)
            if self._pending is not p:
                continue  # superseded while the POST was in flight
            if status == 200:
                self._pending = None
                self._clear_persisted()
                self.metrics.set_gauge("outbox_pending", 0)
                self.n_updates += 1
                self.metrics.inc("updates_delivered")
                # fire-and-forget: shipping spans must neither delay the
                # next slot nor add an await window between the slot
                # snapshot and its use (the BTL003 staleness rule)
                self._ship_task = asyncio.ensure_future(
                    self._ship_spans(
                        tracing.make_trace_id(self.name, p.round_name)
                    )
                )
                continue
            if status == 410:
                # the round is gone (aborted, force-ended, or we were
                # dropped from it): this update can never land
                self._cancel_pending("round_gone")
                continue
            # undeliverable right now (connection refused, 5xx, 401,
            # 429 backpressure): keep the slot and back off
            p.attempts += 1
            self.metrics.inc("update_retries")
            # backoff is computed from the slot snapshot BEFORE the
            # re-register await below can yield: if this update is
            # superseded while rejoining, the loop head re-checks slot
            # identity rather than touching the stale object again
            delay = min(base * (2 ** (p.attempts - 1)), cap)
            delay *= 0.5 + random.random() / 2
            if retry_after is not None:
                delay = max(delay, retry_after)
            if status == 429:
                self.metrics.inc("update_backpressure_429")
            if status == 401:
                # manager restarted without its registry: rejoin, then
                # retry the SAME update under the new credentials
                await self.register_with_manager()
            await asyncio.sleep(delay)

    async def _ship_spans(self, trace_id: str) -> None:
        """Ship this round's finished spans upstream (``POST
        /{name}/trace_spans``) so the manager's trace endpoint serves
        the distributed round in one document. Best-effort and
        fire-after-delivery: spans are observability, not protocol
        state — a failed ship drops them (counted) rather than blocking
        or re-queueing the outbox."""
        spans = self.tracer.drain(trace_id)
        if not spans:
            return
        url = (
            self.manager_url
            + f"trace_spans?client_id={self.client_id}&key={self.key}"
        )
        try:
            async with self._session.post(url, json=spans) as resp:
                if resp.status == 200:
                    self.metrics.inc("trace_spans_shipped", len(spans))
                else:
                    self.metrics.inc("trace_ship_failed")
        except (aiohttp.ClientError, asyncio.TimeoutError):
            self.metrics.inc("trace_ship_failed")

    @staticmethod
    def _retry_after_s(resp) -> Optional[float]:
        """Parse a Retry-After header (seconds form) from a response;
        None when absent/unparseable."""
        val = resp.headers.get("Retry-After")
        if val is None:
            return None
        try:
            return max(0.0, float(val))
        except ValueError:
            return None

    async def _post_update(
        self, p: _PendingUpdate
    ) -> Tuple[Optional[int], Optional[float]]:
        """One delivery attempt; ``(status, retry_after_s)`` — status is
        None on transport failure. The URL is rebuilt per attempt:
        credentials may have rotated via a 401 → re-register cycle
        between attempts. Bodies above ``upload_chunk_bytes`` go through
        the chunked resumable path."""
        chunked = (
            self.upload_chunk_bytes is not None
            and len(p.body) > self.upload_chunk_bytes
        )
        # the outbox task may outlive the round task's copied context:
        # derive the round's trace id from the slot itself so a retry
        # hours later (or after a crash-reload) still joins the right
        # trace, parented to the round's deterministic root span
        trace_id = tracing.make_trace_id(self.name, p.round_name)
        # masked bodies always go direct: the edge cannot partial-fold
        # ring elements (unmasking only works on the full cohort sum)
        via_edge = self._via_edge() and not p.masked
        base_url = self.edge_url if via_edge else self.root_url
        with self.tracer.span(
            "upload", trace_id=trace_id,
            parent_id=tracing.root_span_id(trace_id),
            round=p.round_name, bytes=len(p.body),
            attempt=p.attempts + 1, chunked=chunked,
            via_edge=via_edge,
        ) as up_sp:
            t_up0 = time.perf_counter()
            if chunked:
                status, retry_after = await self._post_update_chunked(
                    p, base_url
                )
                up_sp.set(status=status)
                if status == 200:
                    # successful deliveries only: a refused or retried
                    # attempt's wall time is backoff, not bandwidth
                    self.metrics.observe(
                        "upload_s", time.perf_counter() - t_up0,
                        exemplar=tracing.current_context(),
                    )
                if status is None and via_edge:
                    self._edge_failed()
                elif (status is None or status == 503) and not via_edge:
                    self._root_failed()
                return status, retry_after
            url = (
                base_url
                + f"update?client_id={self.client_id}&key={self.key}"
            )
            try:
                async with self._session.post(
                    url, data=p.body,
                    headers=trace_headers(
                        {"Content-Type": wire.CONTENT_TYPE}
                    ),
                ) as resp:
                    up_sp.set(status=resp.status)
                    if resp.status == 200:
                        self.metrics.observe(
                            "upload_s", time.perf_counter() - t_up0,
                            exemplar=tracing.current_context(),
                        )
                    if resp.status == 409 and via_edge:
                        # the edge refused to fold (secure round, round
                        # unknown): mark the route down so the outbox's
                        # next attempt delivers direct to the root
                        self._edge_failed()
                    if resp.status == 503 and not via_edge:
                        # a standby refusing to serve: rotate the root
                        # ring so the backoff retry lands on the active
                        self._root_failed()
                    return resp.status, self._retry_after_s(resp)
            except (aiohttp.ClientError, asyncio.TimeoutError):
                # manager down; the backoff loop keeps trying
                up_sp.set(status=None)
                if via_edge:
                    self._edge_failed()
                else:
                    self._root_failed()
                return None, None

    async def _post_update_chunked(
        self, p: _PendingUpdate, base_url: Optional[str] = None
    ) -> Tuple[Optional[int], Optional[float]]:
        """Deliver one update as offset/total-framed PUT chunks.

        One attempt = a committed-offset probe + the remaining chunks in
        order. A transport failure returns ``(None, None)`` and the
        outbox backoff retries — the manager keeps the committed prefix,
        so the next attempt's probe resumes where this one died instead
        of re-sending the whole body. The final chunk's 200 IS the
        update's acceptance ack."""
        total = len(p.body)
        base = (
            (base_url if base_url is not None else self.manager_url)
            + f"update_chunk/{p.update_id}"
            + f"?client_id={self.client_id}&key={self.key}"
        )
        try:
            # called under _post_update's "upload" span: trace_headers()
            # picks the active context up, so the probe and every PUT
            # below carry the same traceparent — the manager's assembly
            # ingest span parents off the final chunk's copy of it
            async with self._session.get(
                base, headers=trace_headers()
            ) as resp:
                if resp.status == 401:
                    return 401, self._retry_after_s(resp)
                if resp.status == 200:
                    data = await resp.json()
                    offset = max(0, min(int(data.get("offset", 0)), total))
                else:
                    offset = 0
        except (aiohttp.ClientError, asyncio.TimeoutError,
                TypeError, ValueError):
            return None, None
        if offset:
            self.metrics.inc("chunk_upload_resumes")
            self.metrics.inc("chunk_bytes_resume_skipped", offset)
        resyncs = 0
        while True:
            end = min(offset + self.upload_chunk_bytes, total)
            url = base + f"&offset={offset}&total={total}"
            try:
                self.metrics.inc("chunk_bytes_put", end - offset)
                async with self._session.put(
                    url, data=p.body[offset:end],
                    headers=trace_headers(
                        {"Content-Type": wire.CONTENT_TYPE}
                    ),
                ) as resp:
                    if resp.status == 409:
                        # the manager's committed offset is authoritative
                        resyncs += 1
                        if resyncs > 8:
                            return None, self._retry_after_s(resp)
                        try:
                            data = await resp.json()
                            offset = max(
                                0, min(int(data.get("offset", 0)), total)
                            )
                        except (TypeError, ValueError):
                            return None, None
                        continue
                    if resp.status != 200:
                        return resp.status, self._retry_after_s(resp)
                    if end >= total:
                        return 200, None
                    try:
                        data = await resp.json()
                        offset = min(
                            total, max(end, int(data.get("offset", end)))
                        )
                    except (TypeError, ValueError):
                        offset = end
            except (aiohttp.ClientError, asyncio.TimeoutError):
                return None, None

    # ------------------------------------------------------------------
    def get_data(self) -> Tuple[dict, int]:
        raise NotImplementedError
