"""HTTP worker runtime — a real (non-simulated) federated client.

Reference counterpart: worker.py:12-127. Same lifecycle — register with
the manager, heartbeat on a period, accept ``round_start`` broadcasts,
train locally, POST the result to ``update`` — with the recorded fixes
(SURVEY §2.9):

* item 5 FIXED — ``round_in_progress`` is actually set/cleared, so the
  409 duplicate-round guard works (it was dead code in the reference).
* item 7 FIXED — training runs via ``asyncio.to_thread`` (and the XLA
  dispatch releases the GIL), so heartbeats keep flowing mid-round; the
  reference blocked its event loop for the whole local run.
* Heartbeat backoff is capped exponential (reference doubled unboundedly,
  worker.py:78 ``# TODO: better backoff``).
* Weights travel as BTW1 tensors, not pickles (pickle decode opt-in).

The training itself is the TPU path: a :class:`LocalTrainer` jitted
multi-epoch run — the reference's Python epoch loop (demo.py:29-49)
compiled into one XLA program.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional, Tuple

import aiohttp
from aiohttp import web
import jax
import numpy as np

from baton_tpu.core.model import FedModel
from baton_tpu.core.training import LocalTrainer, make_local_trainer
from baton_tpu.ops.padding import pad_dataset, round_up
from baton_tpu.server import wire
from baton_tpu.server.state import params_to_state_dict, state_dict_to_params
from baton_tpu.server.utils import PeriodicTask

GetData = Callable[[], Tuple[dict, int]]
MAX_BACKOFF = 60.0


class ExperimentWorker:
    """Subclass and implement ``get_data() -> (data_dict, n_samples)``
    (reference worker.py:126-127), or pass ``get_data=`` callable."""

    def __init__(
        self,
        app: web.Application,
        model: FedModel,
        manager: str,
        name: Optional[str] = None,
        port: int = 8080,
        heartbeat_time: float = 60.0,
        worker_host: Optional[str] = None,
        trainer: Optional[LocalTrainer] = None,
        get_data: Optional[GetData] = None,
        allow_pickle: bool = False,
        rng_seed: int = 0,
        auto_register: bool = True,
    ):
        self.name = name or getattr(model, "name", "fedmodel")
        self.model = model
        self.trainer = trainer or make_local_trainer(model)
        self.app = app
        self.port = port
        self.worker_host = worker_host
        self.manager = manager
        self.manager_url = f"http://{manager}/{self.name}/"
        self.allow_pickle = allow_pickle
        if get_data is not None:
            self.get_data = get_data  # type: ignore[assignment]

        self.params = model.init(jax.random.key(rng_seed))
        self.rng = jax.random.key(rng_seed + 1)

        self.client_id: Optional[str] = None
        self.key: Optional[str] = None
        self.n_updates = 0
        self.round_in_progress = False
        self.last_update: Optional[str] = None
        self.heartbeat_time = heartbeat_time
        self._heartbeat_task: Optional[PeriodicTask] = None
        self._register_lock = asyncio.Lock()
        self.__session: Optional[aiohttp.ClientSession] = None

        # secure aggregation (server/secure.py): per-round DH state.
        # {round_name: sk}; bounded to the two most recent rounds so a
        # long-lived worker doesn't accumulate keys.
        self._secure_sk: dict = {}
        # {round_name: {"cohort": [...], "pks": {cid: int}, "scale_bits": n}}
        self._secure_ctx: dict = {}
        # reveal budget: refuse to treat more than this fraction of the
        # cohort as "dropped" in one round — bounds how many clients a
        # protocol-deviating manager could unmask via fake dropout claims
        # (see secure.py threat model; full Bonawitz double-masking is
        # the complete fix)
        self.max_reveal_fraction = 1 / 3
        self._revealed: dict = {}  # {round_name: set(dropped ids revealed)}

        app.router.add_post(f"/{self.name}/round_start", self.handle_round_start)
        app.router.add_post(f"/{self.name}/secure_keys", self.handle_secure_keys)
        app.router.add_get(f"/{self.name}/reveal", self.handle_reveal)
        if auto_register:
            app.on_startup.append(self._on_startup)
            app.on_cleanup.append(self._on_cleanup)

    async def _on_startup(self, app=None) -> None:
        asyncio.ensure_future(self.register_with_manager())

    async def _on_cleanup(self, app=None) -> None:
        if self._heartbeat_task is not None:
            await self._heartbeat_task.stop()
        if self.__session is not None:
            await self.__session.close()

    @property
    def _session(self) -> aiohttp.ClientSession:
        if self.__session is None:
            self.__session = aiohttp.ClientSession()
        return self.__session

    # -- membership ----------------------------------------------------
    async def register_with_manager(self) -> None:
        if self._register_lock.locked():
            return  # collision guard (reference ensure_no_collision, per-instance now)
        async with self._register_lock:
            url = self.manager_url + "register"
            payload = {"url": self.worker_host, "port": self.port}
            backoff = 1.0
            while True:
                try:
                    async with self._session.get(url, json=payload) as resp:
                        data = await resp.json()
                        self.client_id = data["client_id"]
                        self.key = data["key"]
                        break
                except aiohttp.ClientError:
                    await asyncio.sleep(backoff)
                    backoff = min(backoff * 2, MAX_BACKOFF)
            # (Re)start the heartbeat loop — unless we're being called
            # FROM it (401 -> re-register path): stopping would cancel
            # the current task ("Task cannot await on itself") and kill
            # heartbeating permanently. The running loop just continues.
            hb = self._heartbeat_task
            inside_heartbeat = hb is not None and hb.is_current_task()
            if not inside_heartbeat:
                if hb is not None:
                    await hb.stop()
                self._heartbeat_task = PeriodicTask(
                    self.heartbeat, self.heartbeat_time
                ).start()

    async def heartbeat(self) -> None:
        url = self.manager_url + "heartbeat"
        backoff = 1.0
        while True:
            try:
                async with self._session.get(
                    url, json={"client_id": self.client_id, "key": self.key}
                ) as resp:
                    if resp.status == 200:
                        return
                    if resp.status == 401:
                        # manager restarted or culled us: rejoin
                        return await self.register_with_manager()
            except aiohttp.ClientError:
                pass
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, MAX_BACKOFF)

    # -- secure aggregation --------------------------------------------
    def _check_manager_auth(self, request: web.Request) -> bool:
        return (
            request.query.get("client_id") == self.client_id
            and request.query.get("key") == self.key
        )

    async def handle_secure_keys(self, request: web.Request) -> web.Response:
        """Round-setup key agreement: generate a fresh DH keypair for the
        named round and return the public key (server/secure.py step 1)."""
        if not self._check_manager_auth(request):
            return web.json_response({"err": "Wrong Client"}, status=404)
        if self.round_in_progress:
            # Mid-round key exchange would rotate the sk a still-running
            # round's upload will be masked with (aborted rounds REUSE
            # round names — reference naming parity), producing masks no
            # peer cancels. Refuse; the manager excludes us this round.
            return web.json_response({"err": "Update in Progress"}, status=409)
        from baton_tpu.server import secure

        data = await request.json()
        round_name = str(data["round"])
        sk, pk = secure.dh_keypair()
        self._secure_sk[round_name] = sk
        while len(self._secure_sk) > 2:  # keep current + previous round
            self._secure_sk.pop(next(iter(self._secure_sk)))
        while len(self._secure_ctx) > 2:
            self._secure_ctx.pop(next(iter(self._secure_ctx)))
        return web.json_response({"pk": f"{pk:x}"})

    async def handle_reveal(self, request: web.Request) -> web.Response:
        """Dropout recovery: reveal this worker's pairwise seed with ONE
        dropped cohort member (never a secret key, never a seed with a
        live reporter — the manager only learns what it needs to cancel
        the dropped client's residual masks)."""
        if not self._check_manager_auth(request):
            return web.json_response({"err": "Wrong Client"}, status=404)
        from baton_tpu.server import secure

        round_name = request.query.get("round", "")
        dropped = request.query.get("dropped", "")
        sk = self._secure_sk.get(round_name)
        ctx = self._secure_ctx.get(round_name)
        if sk is None or ctx is None:
            return web.json_response({"err": "Unknown Round"}, status=410)
        pk = ctx["pks"].get(dropped)
        if pk is None or dropped == self.client_id:
            return web.json_response({"err": "Unknown Client"}, status=400)
        revealed = self._revealed.setdefault(round_name, set())
        budget = max(1, int(len(ctx["cohort"]) * self.max_reveal_fraction))
        if dropped not in revealed and len(revealed) >= budget:
            # a manager claiming this many dropouts is either facing a
            # catastrophic cohort failure or fabricating dropout claims
            # to unmask clients — either way, refuse (the round aborts)
            return web.json_response({"err": "Reveal Budget"}, status=429)
        revealed.add(dropped)
        while len(self._revealed) > 2:
            self._revealed.pop(next(iter(self._revealed)))
        seed = secure.dh_shared_seed(sk, pk, round_name)
        return web.json_response({"seed": seed.hex()})

    # -- rounds --------------------------------------------------------
    async def handle_round_start(self, request: web.Request) -> web.Response:
        if self.round_in_progress:
            return web.json_response({"err": "Update in Progress"}, status=409)
        if (
            request.query.get("client_id") != self.client_id
            or request.query.get("key") != self.key
        ):
            asyncio.ensure_future(self.register_with_manager())
            return web.json_response({"err": "Wrong Client"}, status=404)
        body = await request.read()
        try:
            tensors, meta = wire.decode_any(
                body, request.content_type, allow_pickle=self.allow_pickle
            )
            round_name = meta["update_name"]
            n_epoch = int(meta["n_epoch"])
            new_params = state_dict_to_params(self.params, tensors)
        except Exception:
            # reject before mutating any state: a bad broadcast must not
            # leave the worker with half-loaded params
            return web.json_response({"err": "Bad Payload"}, status=400)
        secure_info = meta.get("secure")
        if secure_info is not None:
            if round_name not in self._secure_sk:
                # key agreement never happened for this round: we cannot
                # produce a correctly-masked upload, and an unmasked one
                # would poison the cohort's modular sum
                return web.json_response({"err": "No Round Keys"}, status=400)
            self._secure_ctx[round_name] = {
                "cohort": list(secure_info["cohort"]),
                "pks": {c: int(p, 16) for c, p in secure_info["pks"].items()},
                "scale_bits": int(secure_info.get("scale_bits", 16)),
            }
        self.params = new_params
        self.last_update = round_name
        self.round_in_progress = True
        asyncio.ensure_future(self._run_round(round_name, n_epoch))
        return web.json_response("OK")

    async def _run_round(self, round_name: str, n_epoch: int) -> None:
        try:
            data, n_samples = self.get_data()
            self.rng, sub = jax.random.split(self.rng)

            def train():
                capacity = round_up(
                    next(iter(data.values())).shape[0], self.trainer.batch_size
                )
                padded, n = pad_dataset(
                    {k: np.asarray(v) for k, v in data.items()}, capacity
                )
                assert n == n_samples or n_samples <= n
                params, _, losses = self.trainer.train(
                    self.params, padded, np.int32(n_samples), sub, n_epoch
                )
                return params, np.asarray(losses)

            params, loss_history = await asyncio.to_thread(train)
            self.params = params
            await self.report_update(round_name, n_samples, loss_history)
        finally:
            self.round_in_progress = False

    async def report_update(
        self, round_name: str, n_samples: int, loss_history
    ) -> None:
        url = (
            self.manager_url
            + f"update?client_id={self.client_id}&key={self.key}"
        )
        meta = {
            "update_name": round_name,
            "n_samples": int(n_samples),
            "loss_history": [float(x) for x in loss_history],
        }
        ctx = self._secure_ctx.get(round_name)
        if ctx is not None:
            # Secure round: upload sample-weighted quantized params plus
            # every pairwise mask — the manager can only use the cohort
            # sum (server/secure.py step 2). Weighting happens client-
            # side because the server cannot scale a masked ring element.
            from baton_tpu.server import secure

            sk = self._secure_sk[round_name]
            seeds = {
                other: secure.dh_shared_seed(sk, pk, round_name)
                for other, pk in ctx["pks"].items()
                if other != self.client_id
            }
            weighted = {
                k: np.asarray(v, np.float64) * float(n_samples)
                for k, v in params_to_state_dict(self.params).items()
            }
            body = wire.encode(
                secure.mask_state_dict(
                    weighted, self.client_id, seeds, ctx["scale_bits"]
                ),
                dict(meta, secure=True, scale_bits=ctx["scale_bits"]),
            )
        else:
            body = wire.encode(params_to_state_dict(self.params), meta)
        try:
            async with self._session.post(
                url, data=body, headers={"Content-Type": wire.CONTENT_TYPE}
            ) as resp:
                if resp.status == 200:
                    self.n_updates += 1
                elif resp.status == 401:
                    await self.register_with_manager()
                # 410: reported a stale round; nothing to do (parity with
                # reference worker.py:123-124)
        except aiohttp.ClientError:
            pass  # manager down; heartbeat loop will re-establish contact

    # ------------------------------------------------------------------
    def get_data(self) -> Tuple[dict, int]:
        raise NotImplementedError
