"""Write-ahead journal for the manager control plane.

The paper's failure model (heartbeat/TTL culling, 401 re-registration)
assumes the *manager* never dies: the client registry — ids, auth keys,
callback URLs — and the running round's state live only in process
memory, so a coordinator crash forgets every credential it ever issued
and silently discards the in-flight round's training. Production FL
coordinators journal exactly this state (Bonawitz et al., *Towards
Federated Learning at Scale*, §4: the "master" persists its state so a
restart is a pause, not an amnesia event).

This module is the durability layer: an append-only JSONL journal of
control-plane *events*, replayed on boot to rebuild the registry and
round state. Model params are NOT journaled — they ride the existing
orbax :class:`baton_tpu.utils.checkpoint.Checkpointer`; the journal
covers the cheap-but-critical metadata the checkpoint does not.

Design points:

* **Event vocabulary** (one JSON object per line, ``{"event": ...}``):
  ``client_registered`` / ``client_dropped`` for membership,
  ``round_started`` / ``round_client_joined`` / ``round_client_dropped``
  / ``update_accepted`` / ``round_ended`` / ``round_aborted`` /
  ``losses_appended`` for rounds. ``update_accepted`` carries the
  upload's dedup key (``update_id``) — the at-least-once worker outbox
  (http_worker.py) may deliver the same update many times, and the
  buffered-aggregation weighting (FedBuff, Nguyen et al.) is only
  correct if each update is folded in exactly once.
* **fsync policy**: ``"always"`` (default — fsync every append; an
  acknowledged state transition survives power loss), ``"never"``
  (flush to the OS only), or a float (minimum seconds between fsyncs —
  bounded-loss batching for hot registries).
* **Compaction**: :meth:`Journal.compact` writes a snapshot of the full
  control-plane state atomically (temp file + rename, same discipline
  as orbax) and truncates the journal. The manager piggybacks this on
  its per-round checkpoint, so the journal only ever holds events since
  the last completed round.
* **Torn writes**: a crash mid-append leaves a partial final line;
  :meth:`Journal.load` skips undecodable lines (warning, not error), so
  recovery always sees the longest valid prefix.

Auth keys are journaled in the clear by necessity — they are what make
"workers keep their credentials across a manager restart" possible.
Treat the journal file like the TLS private key: same filesystem
permissions, same operator.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

_log = logging.getLogger(__name__)

SNAPSHOT_SUFFIX = ".snapshot"


class Journal:
    """Append-only JSONL event log with snapshot+truncate compaction."""

    def __init__(self, path: str, fsync: Any = "always"):
        if fsync not in ("always", "never") and not isinstance(
            fsync, (int, float)
        ):
            raise ValueError(
                f"fsync must be 'always', 'never' or seconds, got {fsync!r}"
            )
        self.path = os.path.abspath(path)
        self.snapshot_path = self.path + SNAPSHOT_SUFFIX
        self.fsync = fsync
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fh = open(self.path, "a", encoding="utf-8")
        self._last_fsync = 0.0
        self.appends = 0

    # ------------------------------------------------------------------
    def append(self, event: str, **fields: Any) -> None:
        """Durably record one control-plane event."""
        rec = {"event": event, **fields}
        self._fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._fh.flush()
        self._maybe_fsync()
        self.appends += 1

    def _maybe_fsync(self) -> None:
        if self.fsync == "never":
            return
        if self.fsync == "always":
            os.fsync(self._fh.fileno())
            return
        now = time.monotonic()
        if now - self._last_fsync >= float(self.fsync):
            os.fsync(self._fh.fileno())
            self._last_fsync = now

    # ------------------------------------------------------------------
    def load(self) -> Tuple[Optional[dict], List[dict]]:
        """(snapshot | None, events) currently on disk — the recovery
        input. Undecodable journal lines (torn final write) are skipped
        with a warning so the longest valid prefix always replays."""
        snapshot = None
        if os.path.exists(self.snapshot_path):
            try:
                with open(self.snapshot_path, "r", encoding="utf-8") as fh:
                    snapshot = json.load(fh)
            except (OSError, json.JSONDecodeError) as exc:
                # a half-written snapshot cannot happen (atomic rename),
                # so this is real corruption — recover from events alone
                _log.warning("journal snapshot unreadable (%s); ignoring", exc)
        events: List[dict] = []
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                for lineno, line in enumerate(fh, 1):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        _log.warning(
                            "journal %s line %d undecodable (torn write?); "
                            "skipped", self.path, lineno)
                        continue
                    if isinstance(rec, dict) and "event" in rec:
                        events.append(rec)
        except OSError:
            pass
        return snapshot, events

    def recover(self) -> "RecoveredState":
        snapshot, events = self.load()
        return replay(snapshot, events)

    # ------------------------------------------------------------------
    def compact(self, snapshot: dict) -> None:
        """Write ``snapshot`` atomically, then truncate the journal.

        Call only at a quiescent point (no round in flight): the
        snapshot schema carries membership and history, not an open
        round, so compacting mid-round would forget it."""
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(snapshot, fh, separators=(",", ":"))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.snapshot_path)
        # events up to here are superseded by the snapshot: truncate
        self._fh.close()
        self._fh = open(self.path, "w", encoding="utf-8")
        self._fh.flush()
        if self.fsync != "never":
            os.fsync(self._fh.fileno())

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.flush()
            if self.fsync != "never":
                os.fsync(self._fh.fileno())
            self._fh.close()

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ----------------------------------------------------------------------
@dataclasses.dataclass
class RecoveredState:
    """Control-plane state rebuilt from snapshot + journal replay."""

    clients: Dict[str, dict] = dataclasses.field(default_factory=dict)
    n_rounds: int = 0
    loss_history: List[float] = dataclasses.field(default_factory=list)
    #: the in-flight round at crash time, or None:
    #: {round_name, meta, participants: [ids], accepted: {cid: update_id}}
    open_round: Optional[dict] = None
    #: True when neither snapshot nor events existed — a fresh journal
    #: must not override e.g. a checkpoint-restored round counter.
    empty: bool = True


def replay(
    snapshot: Optional[dict], events: Iterable[dict]
) -> RecoveredState:
    """Fold snapshot + events into the state the manager died with.

    Replay is pure and total: unknown event types are ignored (forward
    compatibility), events referencing unknown clients/rounds are
    no-ops, so any valid journal prefix produces a usable state."""
    st = RecoveredState()
    if snapshot:
        st.empty = False
        st.clients = {
            str(cid): dict(c) for cid, c in (snapshot.get("clients") or {}).items()
        }
        st.n_rounds = int(snapshot.get("n_rounds", 0))
        st.loss_history = [float(x) for x in snapshot.get("loss_history", [])]
    for ev in events:
        st.empty = False
        kind = ev.get("event")
        cid = ev.get("client_id")
        if kind == "client_registered":
            st.clients[cid] = {
                k: ev.get(k)
                for k in ("key", "remote", "port", "url", "registered_at")
            }
            st.clients[cid].setdefault("num_updates", 0)
        elif kind == "client_dropped":
            st.clients.pop(cid, None)
            if st.open_round is not None:
                st.open_round["participants"].discard(cid)
                st.open_round["accepted"].pop(cid, None)
        elif kind == "round_started":
            st.open_round = {
                "round_name": ev.get("round_name"),
                "meta": ev.get("meta") or {},
                "participants": set(),
                "accepted": {},
            }
        elif kind == "round_client_joined":
            if st.open_round is not None:
                st.open_round["participants"].add(cid)
        elif kind == "round_client_dropped":
            if st.open_round is not None:
                st.open_round["participants"].discard(cid)
                st.open_round["accepted"].pop(cid, None)
        elif kind == "update_accepted":
            if st.open_round is not None:
                st.open_round["accepted"][cid] = ev.get("update_id")
            c = st.clients.get(cid)
            if c is not None:
                c["num_updates"] = int(c.get("num_updates") or 0) + 1
                c["last_update"] = ev.get("round_name")
        elif kind == "round_ended":
            st.n_rounds = int(ev.get("n_rounds", st.n_rounds + 1))
            st.open_round = None
        elif kind == "round_aborted":
            st.open_round = None
        elif kind == "losses_appended":
            st.loss_history.extend(float(x) for x in ev.get("values", []))
    return st


def registry_snapshot(registry) -> Dict[str, dict]:
    """The per-client snapshot schema (mirrors ``client_registered``
    event fields) from a live :class:`ClientRegistry`."""
    return {
        cid: {
            "key": c.key,
            "remote": c.remote,
            "port": c.port,
            "url": c.url,
            "registered_at": c.registered_at,
            "num_updates": c.num_updates,
            "last_update": c.last_update,
        }
        for cid, c in registry.clients.items()
    }
