"""Write-ahead journal for the manager control plane.

The paper's failure model (heartbeat/TTL culling, 401 re-registration)
assumes the *manager* never dies: the client registry — ids, auth keys,
callback URLs — and the running round's state live only in process
memory, so a coordinator crash forgets every credential it ever issued
and silently discards the in-flight round's training. Production FL
coordinators journal exactly this state (Bonawitz et al., *Towards
Federated Learning at Scale*, §4: the "master" persists its state so a
restart is a pause, not an amnesia event).

This module is the durability layer: an append-only JSONL journal of
control-plane *events*, replayed on boot to rebuild the registry and
round state. Model params are NOT journaled — they ride the existing
orbax :class:`baton_tpu.utils.checkpoint.Checkpointer`; the journal
covers the cheap-but-critical metadata the checkpoint does not.

Design points:

* **Event vocabulary** (one JSON object per line, ``{"event": ...}``):
  ``client_registered`` / ``client_dropped`` for membership,
  ``round_started`` / ``round_client_joined`` / ``round_client_dropped``
  / ``update_accepted`` / ``round_ended`` / ``round_aborted`` /
  ``losses_appended`` for rounds. ``update_accepted`` carries the
  upload's dedup key (``update_id``) — the at-least-once worker outbox
  (http_worker.py) may deliver the same update many times, and the
  buffered-aggregation weighting (FedBuff, Nguyen et al.) is only
  correct if each update is folded in exactly once.
* **fsync policy**: ``"always"`` (default — fsync every append; an
  acknowledged state transition survives power loss), ``"never"``
  (flush to the OS only), or a float (minimum seconds between fsyncs —
  bounded-loss batching for hot registries).
* **Compaction**: :meth:`Journal.compact` writes a snapshot of the full
  control-plane state atomically (temp file + rename, same discipline
  as orbax) and truncates the journal. The manager piggybacks this on
  its per-round checkpoint, so the journal only ever holds events since
  the last completed round.
* **Torn writes**: a crash mid-append leaves a partial final line;
  :meth:`Journal.load` skips undecodable lines (warning, not error), so
  recovery always sees the longest valid prefix.

Auth keys are what make "workers keep their credentials across a
manager restart" possible, so they must be journaled — but not in the
clear: when ``BATON_JOURNAL_KEY`` is set (a passphrase, or a path to a
file holding one) every ``key`` field — and the ``data`` body of
``update_payload`` events, which carries a client's model update —
is wrapped at the append/compact boundary (``enc1:`` envelope:
HMAC-SHA256 keystream + truncated-HMAC tag, stdlib only) and unwrapped
transparently on load. Legacy plaintext journals keep reading as-is —
migration is "set the env var and let the next compaction rewrite the
snapshot". A wrapped key that cannot be unwrapped (env var lost, or
wrong) degrades to ``None``: the client re-registers instead of anyone
trusting an unverifiable credential; an unverifiable payload likewise
degrades to None, so recovery rebroadcasts the round rather than
replaying bytes it cannot authenticate. Replication
(:mod:`baton_tpu.server.replication`) ships journal bytes verbatim, so
standbys see only wrapped keys/payloads on the wire and need the same
``BATON_JOURNAL_KEY`` to serve after promotion.
"""

from __future__ import annotations

import dataclasses
import hashlib
import hmac
import json
import logging
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

_log = logging.getLogger(__name__)

SNAPSHOT_SUFFIX = ".snapshot"

#: env var naming the at-rest wrap key: either the passphrase itself or
#: a path to a file whose (stripped) contents are the passphrase
WRAP_KEY_ENV = "BATON_JOURNAL_KEY"
_WRAP_PREFIX = "enc1:"


def load_wrap_key(env: str = WRAP_KEY_ENV) -> Optional[bytes]:
    """Resolve the at-rest wrap key from the environment; None (no
    wrapping) when unset. A value that names a readable file is
    dereferenced so the secret can live outside the process table."""
    raw = os.environ.get(env)
    if not raw:
        return None
    if os.path.isfile(raw):
        try:
            with open(raw, "r", encoding="utf-8") as fh:
                raw = fh.read().strip()
        except OSError as exc:
            _log.warning("%s names an unreadable file (%s); at-rest key "
                         "wrapping disabled", env, exc)
            return None
        if not raw:
            return None
    return hashlib.sha256(raw.encode("utf-8")).digest()


def _keystream(wk: bytes, nonce: bytes, n: int) -> bytes:
    out = bytearray()
    counter = 0
    while len(out) < n:
        out.extend(hmac.new(wk, b"ks" + nonce + counter.to_bytes(4, "big"),
                            hashlib.sha256).digest())
        counter += 1
    return bytes(out[:n])


def wrap_value(plain: str, wk: bytes) -> str:
    """``enc1:<nonce>:<ciphertext>:<tag>`` (hex fields) — encrypt-then-
    MAC with independent HMAC-derived keystream and tag, stdlib only
    (the serving image carries no cryptography package)."""
    nonce = os.urandom(12)
    pt = plain.encode("utf-8")
    ct = bytes(a ^ b for a, b in zip(pt, _keystream(wk, nonce, len(pt))))
    tag = hmac.new(wk, b"tag" + nonce + ct, hashlib.sha256).digest()[:16]
    return _WRAP_PREFIX + nonce.hex() + ":" + ct.hex() + ":" + tag.hex()


def unwrap_value(value: Any, wk: Optional[bytes]) -> Optional[str]:
    """Inverse of :func:`wrap_value` with two deliberate degradations:
    a non-``enc1:`` value passes through untouched (legacy plaintext
    journals), and a wrapped value that cannot be verified — missing
    key, wrong key, mangled envelope — becomes None so the client
    re-registers rather than anyone trusting an unchecked credential."""
    if not isinstance(value, str) or not value.startswith(_WRAP_PREFIX):
        return value
    if wk is None:
        _log.warning("journal holds wrapped auth keys but %s is unset; "
                     "dropping keys (clients will re-register)",
                     WRAP_KEY_ENV)
        return None
    try:
        nonce_hex, ct_hex, tag_hex = value[len(_WRAP_PREFIX):].split(":")
        nonce = bytes.fromhex(nonce_hex)
        ct = bytes.fromhex(ct_hex)
        tag = bytes.fromhex(tag_hex)
    except ValueError:
        return None
    want = hmac.new(wk, b"tag" + nonce + ct, hashlib.sha256).digest()[:16]
    if not hmac.compare_digest(tag, want):
        _log.warning("journaled auth key failed unwrap (wrong %s?); "
                     "dropping key", WRAP_KEY_ENV)
        return None
    pt = bytes(a ^ b for a, b in zip(ct, _keystream(wk, nonce, len(ct))))
    return pt.decode("utf-8", "replace")


class Journal:
    """Append-only JSONL event log with snapshot+truncate compaction."""

    def __init__(self, path: str, fsync: Any = "always"):
        if fsync not in ("always", "never") and not isinstance(
            fsync, (int, float)
        ):
            raise ValueError(
                f"fsync must be 'always', 'never' or seconds, got {fsync!r}"
            )
        self.path = os.path.abspath(path)
        self.snapshot_path = self.path + SNAPSHOT_SUFFIX
        self.fsync = fsync
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fh = open(self.path, "a", encoding="utf-8")
        self._last_fsync = 0.0
        self.appends = 0
        #: bumps on every compaction — the WAL shipper's frame id, since
        #: compaction truncates the file and resets byte offsets
        self.generation = 0
        #: makes (generation, journal bytes, snapshot file) one atomic
        #: frame: the WAL shipper builds segments on a worker thread
        #: holding this, so a compaction (truncate + generation bump) on
        #: the loop can never tear a segment mid-read.  A threading.Lock
        #: because the reader is NOT on the event loop.
        self.io_lock = threading.Lock()
        self._wrap_key = load_wrap_key()

    # ------------------------------------------------------------------
    def append(self, event: str, **fields: Any) -> None:
        """Durably record one control-plane event."""
        if self._wrap_key is not None and isinstance(fields.get("key"), str):
            fields = dict(fields, key=wrap_value(fields["key"],
                                                 self._wrap_key))
        if (self._wrap_key is not None and event == "update_payload"
                and isinstance(fields.get("data"), str)):
            # a journaled upload body is model-update content — at rest
            # it gets the same envelope as auth keys, and the WAL ships
            # it wrapped so standbys never hold plaintext training bytes
            fields = dict(fields, data=wrap_value(fields["data"],
                                                  self._wrap_key))
        rec = {"event": event, **fields}
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        # brief critical section: a segment build holding io_lock on a
        # worker thread sees either all of this append or none of it
        with self.io_lock:
            self._fh.write(line)
            self._fh.flush()
            self._maybe_fsync()
            self.appends += 1

    def _maybe_fsync(self) -> None:
        if self.fsync == "never":
            return
        if self.fsync == "always":
            os.fsync(self._fh.fileno())
            return
        now = time.monotonic()
        if now - self._last_fsync >= float(self.fsync):
            os.fsync(self._fh.fileno())
            self._last_fsync = now

    # ------------------------------------------------------------------
    def load(self) -> Tuple[Optional[dict], List[dict]]:
        """(snapshot | None, events) currently on disk — the recovery
        input. Undecodable journal lines (torn final write) are skipped
        with a warning so the longest valid prefix always replays."""
        snapshot = None
        if os.path.exists(self.snapshot_path):
            try:
                with open(self.snapshot_path, "r", encoding="utf-8") as fh:
                    snapshot = json.load(fh)
            except (OSError, json.JSONDecodeError) as exc:
                # a half-written snapshot cannot happen (atomic rename),
                # so this is real corruption — recover from events alone
                _log.warning("journal snapshot unreadable (%s); ignoring", exc)
        events: List[dict] = []
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                for lineno, line in enumerate(fh, 1):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        _log.warning(
                            "journal %s line %d undecodable (torn write?); "
                            "skipped", self.path, lineno)
                        continue
                    if isinstance(rec, dict) and "event" in rec:
                        events.append(rec)
        except OSError:
            pass
        # transparent at-rest unwrap: plaintext legacy values pass
        # through, unverifiable wrapped values degrade to None
        if snapshot:
            for c in (snapshot.get("clients") or {}).values():
                if isinstance(c, dict) and "key" in c:
                    c["key"] = unwrap_value(c["key"], self._wrap_key)
        for rec in events:
            if "key" in rec:
                rec["key"] = unwrap_value(rec["key"], self._wrap_key)
            if rec.get("event") == "update_payload" and "data" in rec:
                # unverifiable body → None → replay keeps the event but
                # _resume_round sees no payload and rebroadcasts; the
                # round degrades to re-training, never to bad tensors
                rec["data"] = unwrap_value(rec["data"], self._wrap_key)
        return snapshot, events

    def recover(self) -> "RecoveredState":
        snapshot, events = self.load()
        return replay(snapshot, events)

    # ------------------------------------------------------------------
    def compact(self, snapshot: dict) -> None:
        """Write ``snapshot`` atomically, then truncate the journal.

        Call only at a quiescent point (no round in flight): the
        snapshot schema carries membership and history, not an open
        round, so compacting mid-round would forget it."""
        if self._wrap_key is not None and snapshot.get("clients"):
            snapshot = dict(snapshot, clients={
                cid: (dict(c, key=wrap_value(c["key"], self._wrap_key))
                      if isinstance(c.get("key"), str)
                      and not c["key"].startswith(_WRAP_PREFIX)
                      else dict(c))
                for cid, c in snapshot["clients"].items()
            })
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(snapshot, fh, separators=(",", ":"))
            fh.flush()
            os.fsync(fh.fileno())
        # io_lock spans snapshot publication, truncation and the
        # generation bump: a concurrent segment build must see the
        # pre-compaction frame or the post-compaction frame, never a
        # fresh snapshot with a stale generation
        with self.io_lock:
            os.replace(tmp, self.snapshot_path)
            # events up to here are superseded by the snapshot: truncate
            self._fh.close()
            self._fh = open(self.path, "w", encoding="utf-8")
            self._fh.flush()
            if self.fsync != "never":
                os.fsync(self._fh.fileno())
            # byte offsets restart from zero — a new shipping generation
            self.generation += 1

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.flush()
            if self.fsync != "never":
                os.fsync(self._fh.fileno())
            self._fh.close()

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ----------------------------------------------------------------------
@dataclasses.dataclass
class RecoveredState:
    """Control-plane state rebuilt from snapshot + journal replay."""

    clients: Dict[str, dict] = dataclasses.field(default_factory=dict)
    n_rounds: int = 0
    loss_history: List[float] = dataclasses.field(default_factory=list)
    #: the in-flight round at crash time, or None:
    #: {round_name, meta, participants: [ids], accepted: {cid: update_id},
    #:  payloads: {cid: {data: b64, content_type}}}
    open_round: Optional[dict] = None
    #: highest leadership epoch ever journaled (``ha_lease`` events +
    #: compaction snapshots) — 0 when replication was never enabled.
    #: A promoting standby serves at ``ha_epoch + 1``.
    ha_epoch: int = 0
    #: True when neither snapshot nor events existed — a fresh journal
    #: must not override e.g. a checkpoint-restored round counter.
    empty: bool = True


def replay(
    snapshot: Optional[dict], events: Iterable[dict]
) -> RecoveredState:
    """Fold snapshot + events into the state the manager died with.

    Replay is pure and total: unknown event types are ignored (forward
    compatibility), events referencing unknown clients/rounds are
    no-ops, so any valid journal prefix produces a usable state."""
    st = RecoveredState()
    if snapshot:
        st.empty = False
        st.clients = {
            str(cid): dict(c) for cid, c in (snapshot.get("clients") or {}).items()
        }
        st.n_rounds = int(snapshot.get("n_rounds", 0))
        st.loss_history = [float(x) for x in snapshot.get("loss_history", [])]
        st.ha_epoch = int(snapshot.get("ha_epoch", 0))
    for ev in events:
        st.empty = False
        kind = ev.get("event")
        cid = ev.get("client_id")
        if kind == "client_registered":
            st.clients[cid] = {
                k: ev.get(k)
                for k in ("key", "remote", "port", "url", "registered_at")
            }
            st.clients[cid].setdefault("num_updates", 0)
        elif kind == "client_dropped":
            st.clients.pop(cid, None)
            if st.open_round is not None:
                st.open_round["participants"].discard(cid)
                st.open_round["accepted"].pop(cid, None)
                st.open_round["payloads"].pop(cid, None)
        elif kind == "round_started":
            st.open_round = {
                "round_name": ev.get("round_name"),
                "meta": ev.get("meta") or {},
                "participants": set(),
                "accepted": {},
                "payloads": {},
            }
        elif kind == "round_client_joined":
            if st.open_round is not None:
                st.open_round["participants"].add(cid)
        elif kind == "round_client_dropped":
            if st.open_round is not None:
                st.open_round["participants"].discard(cid)
                st.open_round["accepted"].pop(cid, None)
                st.open_round["payloads"].pop(cid, None)
        elif kind == "update_payload":
            # the accepted upload's bytes, riding the WAL so a standby
            # can finish the round without re-training the reporter
            if (st.open_round is not None
                    and ev.get("round_name") == st.open_round["round_name"]):
                st.open_round["payloads"][cid] = {
                    "data": ev.get("data"),
                    "content_type": ev.get("content_type"),
                }
        elif kind == "ha_lease":
            with_epoch = ev.get("epoch")
            if isinstance(with_epoch, (int, float)):
                st.ha_epoch = max(st.ha_epoch, int(with_epoch))
        elif kind == "update_accepted":
            if st.open_round is not None:
                st.open_round["accepted"][cid] = ev.get("update_id")
            c = st.clients.get(cid)
            if c is not None:
                c["num_updates"] = int(c.get("num_updates") or 0) + 1
                c["last_update"] = ev.get("round_name")
        elif kind == "round_ended":
            st.n_rounds = int(ev.get("n_rounds", st.n_rounds + 1))
            st.open_round = None
        elif kind == "round_aborted":
            st.open_round = None
        elif kind == "losses_appended":
            st.loss_history.extend(float(x) for x in ev.get("values", []))
    return st


def registry_snapshot(registry) -> Dict[str, dict]:
    """The per-client snapshot schema (mirrors ``client_registered``
    event fields) from a live :class:`ClientRegistry`."""
    return {
        cid: {
            "key": c.key,
            "remote": c.remote,
            "port": c.port,
            "url": c.url,
            "registered_at": c.registered_at,
            "num_updates": c.num_updates,
            "last_update": c.last_update,
        }
        for cid, c in registry.clients.items()
    }
