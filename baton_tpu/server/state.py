"""Bridge between params pytrees and flat named state dicts.

The wire protocol and the reference semantics (manager.py:119-126) speak
flat ``{name: tensor}`` state dicts; the TPU core speaks pytrees. Names
are slash-joined tree paths (``"conv1/w"``), stable across processes for
any JSON-style pytree (dicts/lists/tuples of arrays).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np

from baton_tpu.core.partition import path_str as _path_str

Params = Any


def params_to_state_dict(params: Params) -> Dict[str, np.ndarray]:
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    return {_path_str(path): np.asarray(leaf) for path, leaf in flat}


def state_dict_to_params(template: Params, state: Dict[str, np.ndarray]) -> Params:
    """Rebuild a pytree shaped like ``template`` from a flat state dict.

    Raises KeyError on missing tensors and ValueError on shape mismatch —
    a malformed upload must not corrupt the global model.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in flat:
        name = _path_str(path)
        if name not in state:
            raise KeyError(f"state dict missing tensor {name!r}")
        arr = np.asarray(state[name])
        if tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(
                f"tensor {name!r} has shape {arr.shape}, expected {tuple(leaf.shape)}"
            )
        leaves.append(arr.astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)
