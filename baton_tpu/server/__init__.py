"""Elastic control plane — the edge of the framework.

The TPU simulation engine (baton_tpu.parallel) covers *simulated*
clients; this package keeps the reference's capability for *real*
external clients: register / heartbeat / cull / re-register membership,
round orchestration, and sample-weighted aggregation of uploaded weights,
speaking the reference wire protocol (SURVEY §2.8: same routes, same
status codes 400/401/404/409/410/423).

Architecture difference from the reference: the round state machine
(:mod:`rounds`) and membership registry (:mod:`registry`) are pure,
clock-injected Python — no asyncio, trivially unit-testable — and the
aiohttp layer (:mod:`http_manager`, :mod:`http_worker`) is a thin
adapter. The reference interleaves both (update_manager.py's state *is*
an asyncio.Lock, client_manager.py owns an aiohttp session).
"""

from baton_tpu.server.rounds import (
    RoundManager,
    RoundError,
    RoundInProgress,
    RoundNotInProgress,
)
from baton_tpu.server.registry import ClientRegistry, AuthError, UnknownClient

__all__ = [
    "RoundManager",
    "RoundError",
    "RoundInProgress",
    "RoundNotInProgress",
    "ClientRegistry",
    "AuthError",
    "UnknownClient",
]
