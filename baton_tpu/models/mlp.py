"""Small MLP classifier (pure-functional, no flax needed)."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from baton_tpu.core.losses import softmax_cross_entropy
from baton_tpu.core.model import FedModel


def mlp_classifier_model(
    in_dim: int,
    hidden: Sequence[int] = (64,),
    n_classes: int = 10,
    name: str = "mlp",
) -> FedModel:
    dims = [in_dim, *hidden, n_classes]

    def init(rng):
        params = []
        for i in range(len(dims) - 1):
            rng, sub = jax.random.split(rng)
            scale = jnp.sqrt(2.0 / dims[i])
            params.append(
                {
                    "w": jax.random.normal(sub, (dims[i], dims[i + 1]), jnp.float32)
                    * scale,
                    "b": jnp.zeros((dims[i + 1],), jnp.float32),
                }
            )
        return params

    def apply(params, batch, rng):
        h = batch["x"].reshape(batch["x"].shape[0], -1)
        for i, layer in enumerate(params):
            h = h @ layer["w"] + layer["b"]
            if i < len(params) - 1:
                h = jax.nn.relu(h)
        return h

    def per_example_loss(params, batch, rng):
        return softmax_cross_entropy(apply(params, batch, rng), batch, rng)

    return FedModel(init=init, apply=apply, per_example_loss=per_example_loss, name=name)
