"""Shared transformer building blocks for the baton_tpu model zoo.

The reference ships no transformer (its only model is a 10->1 linear
layer, reference demo.py:15-49); BASELINE configs 3-5 (BERT/AG-News
FedProx, Llama-class LoRA instruction-tune, ViT-B/16 DP cross-silo) are
driver-set workloads that need one. These blocks are written TPU-first:

* **Everything is einsum/matmul** on [B, L, D]-shaped activations so XLA
  tiles the projections and the attention contractions onto the MXU;
  params stay fp32 (FedAvg accumulates fp32), activations are cast to a
  ``compute_dtype`` (bf16 on TPU) per-apply, norms/softmax in fp32.
* **Static shapes only** — causal masking is a static ``L x L`` bound
  inside the kernel, padding is a dynamic length vector turned into an
  additive bias; no data-dependent control flow, so the whole model jits
  and vmaps over a simulated-client axis.
* **Injectable attention kernel**: every model takes an ``attention_fn``
  with the signature of :func:`dot_product_attention` so the dense
  kernel can be swapped for a fused/blockwise kernel or ring attention
  over a sequence mesh axis without touching model code.
* **GQA layout** [B, H, L, Dh] with an explicit kv-head axis: K/V heads
  are broadcast to query groups by reshape, not materialized repeats.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

# attention_fn(q, k, v, bias, causal) -> out
#   q [B, Hq, L, Dh], k/v [B, Hkv, L, Dh], bias None or [B, 1, 1, L] additive
AttentionFn = Callable[..., jax.Array]


# ---------------------------------------------------------------------------
# initializers


def normal_init(key, shape, stddev):
    return jax.random.normal(key, shape, jnp.float32) * stddev


def dense_init(key, d_in, d_out, stddev=None):
    """[d_in, d_out] fan-in scaled normal (stddev 1/sqrt(d_in) default)."""
    if stddev is None:
        stddev = d_in ** -0.5
    return normal_init(key, (d_in, d_out), stddev)


def ln_init(d):
    return {"scale": jnp.ones((d,), jnp.float32), "bias": jnp.zeros((d,), jnp.float32)}


def rms_init(d):
    return {"scale": jnp.ones((d,), jnp.float32)}


# ---------------------------------------------------------------------------
# norms (fp32 stats regardless of compute dtype)


def layer_norm(x, p, eps=1e-6):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (xf * p["scale"] + p["bias"]).astype(x.dtype)


def rms_norm(x, p, eps=1e-6):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(ms + eps) * p["scale"]).astype(x.dtype)


# ---------------------------------------------------------------------------
# rotary position embeddings (RoPE)


def rope_angles(seq_len: int, head_dim: int, theta: float = 10000.0):
    """Returns (cos, sin) each [L, Dh/2], fp32."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    pos = jnp.arange(seq_len, dtype=jnp.float32)
    ang = jnp.outer(pos, inv_freq)  # [L, Dh/2]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x, cos, sin):
    """Rotate pairs of channels. x [B, H, L, Dh]; cos/sin [L, Dh/2]."""
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    # broadcast [L, Dh/2] over [B, H, L, Dh/2]
    r1 = x1 * cos - x2 * sin
    r2 = x2 * cos + x1 * sin
    return jnp.concatenate([r1, r2], axis=-1).astype(x.dtype)


# ---------------------------------------------------------------------------
# attention


def dot_product_attention(q, k, v, bias=None, causal=False):
    """Dense scaled-dot-product attention with GQA.

    q [B, Hq, L, Dh]; k, v [B, Hkv, L, Dh] with Hq % Hkv == 0. Softmax in
    fp32; the two contractions are einsums XLA maps onto the MXU. ``bias``
    is additive, broadcastable to [B, Hq, L, L] (padding uses -inf-like
    large negatives).
    """
    b, hq, l, dh = q.shape
    hkv = k.shape[1]
    scale = dh ** -0.5
    if hq != hkv:
        q = q.reshape(b, hkv, hq // hkv, l, dh)
        scores = jnp.einsum("bhgqd,bhkd->bhgqk", q, k) * scale
        scores = scores.reshape(b, hq, l, l)
    else:
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    scores = scores.astype(jnp.float32)
    if bias is not None:
        scores = scores + bias
    if causal:
        ql = jnp.arange(l)
        scores = jnp.where(ql[:, None] >= ql[None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    if hq != hkv:
        probs = probs.reshape(b, hkv, hq // hkv, l, l)
        out = jnp.einsum("bhgqk,bhkd->bhgqd", probs, v)
        return out.reshape(b, hq, l, dh)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# dense materializes a [B, Hq, Lq, Lk] fp32 score tensor; beyond this
# budget (or past the length where the Pallas kernel measures faster —
# 4.1x at L=4096 on v5e, see bench.py's attention micro-bench) the
# flash kernel takes over. The crossover and block shapes are module
# state so a measured sweep can recalibrate them per process
# (configure_attention_dispatch below).
_FLASH_MIN_LEN = 4096
# None = inherit the kernel's own block defaults (and their internal
# sequence clamping/alignment); a (block_q, block_k) tuple only after a
# measured sweep configured one
_FLASH_BLOCKS = None
_DENSE_SCORES_BUDGET_BYTES = 512 * 1024 ** 2


def configure_attention_dispatch(min_len=None, blocks=None,
                                 sweep_path=None):
    """Apply a MEASURED flash-vs-dense crossover to the dispatcher.

    Explicit ``min_len`` / ``blocks`` win. Otherwise ``sweep_path``
    names an artifact written by ``benchmarks/attention_sweep.py`` on
    hardware (``attention_sweep_tpu.json``): the threshold becomes the
    smallest measured L whose best flash block config beats the dense
    einsum, and the dispatcher adopts that config's (block_q, block_k).
    Only ``platform == "tpu"`` artifacts are trusted — a CPU/interpret
    sweep must never steer the TPU dispatch. Returns the
    ``(min_len, (block_q, block_k))`` now in effect; no-ops (returning
    current state) when the artifact is missing/foreign or shows no
    crossover.
    """
    global _FLASH_MIN_LEN, _FLASH_BLOCKS
    if sweep_path is not None and min_len is None and blocks is None:
        import json

        try:
            with open(sweep_path) as f:
                sweep = json.load(f)
            records = (sorted(sweep.get("results", []),
                              key=lambda r: r.get("L", 1 << 30))
                       if sweep.get("platform") == "tpu" else [])
        except (OSError, ValueError, AttributeError):
            records = []
        for rec in records:
            # per-record tolerance: one malformed row (a null timing, a
            # foreign shape) must not discard the valid rows after it
            try:
                dense = rec.get("dense_ms")
                flash = rec.get("flash") or {}
                if not (isinstance(dense, (int, float)) and flash):
                    continue
                spec, ms = min(flash.items(), key=lambda kv: kv[1])
                if ms < dense:
                    min_len = rec["L"]
                    blocks = tuple(int(x) for x in spec.split("x"))
                    break
            except (ValueError, KeyError, TypeError, AttributeError):
                continue
    if min_len is not None:
        _FLASH_MIN_LEN = int(min_len)
    if blocks is not None:
        _FLASH_BLOCKS = (int(blocks[0]), int(blocks[1]))
    return _FLASH_MIN_LEN, _FLASH_BLOCKS


def default_attention(q, k, v, bias=None, causal=False):
    """Backend-dispatching attention — the model zoo's default kernel.

    On TPU, long sequences route to the Pallas flash-attention kernel
    (ops/flash_attention.py): O(L·block) memory instead of the dense
    [B, H, L, L] score tensor, fused online softmax, same numerics
    (fp32 softmax, GQA), measured 4x faster than the dense einsum at
    L=4096 on v5e. Short sequences stay on the dense path — XLA's fused
    attention wins there (measured crossover ~2-4k), and so does every
    non-TPU backend (CPU tests would hit the interpreted Pallas kernel).

    The dispatch happens at trace time (shapes and
    ``jax.default_backend()`` are ordinary Python), so the jitted
    program contains exactly one kernel — there is no runtime branch.
    A ``bias`` that is not the standard per-key [B, 1, 1, L] padding
    bias falls back to the dense kernel, which accepts anything
    broadcastable to [B, Hq, L, L].
    """
    if jax.default_backend() == "tpu":
        b, hq, lq, _ = q.shape
        lk = k.shape[2]
        scores_bytes = 4 * b * hq * lq * lk
        if (lk >= _FLASH_MIN_LEN or scores_bytes > _DENSE_SCORES_BUDGET_BYTES) and (
            bias is None or bias.shape == (b, 1, 1, lk)
        ):
            from baton_tpu.ops.flash_attention import flash_attention

            kw = ({} if _FLASH_BLOCKS is None else
                  {"block_q": _FLASH_BLOCKS[0],
                   "block_k": _FLASH_BLOCKS[1]})
            return flash_attention(q, k, v, bias=bias, causal=causal, **kw)
    return dot_product_attention(q, k, v, bias=bias, causal=causal)


def padding_bias(mask, dtype=jnp.float32):
    """[B, L] 1/0 validity mask -> additive [B, 1, 1, L] attention bias."""
    return ((1.0 - mask.astype(jnp.float32)) * -1e30)[:, None, None, :].astype(dtype)


def mha_init(key, d_model, n_heads, n_kv_heads=None, head_dim=None, out_std=None):
    """Fused QKV-per-role projection params for (G)MQA attention."""
    n_kv = n_kv_heads or n_heads
    dh = head_dim or d_model // n_heads
    kq, kk, kv, ko = jax.random.split(key, 4)
    return {
        "wq": dense_init(kq, d_model, n_heads * dh),
        "wk": dense_init(kk, d_model, n_kv * dh),
        "wv": dense_init(kv, d_model, n_kv * dh),
        "wo": dense_init(ko, n_heads * dh, d_model, stddev=out_std),
    }


def mha_apply(
    p,
    x,
    n_heads: int,
    n_kv_heads: Optional[int] = None,
    bias=None,
    causal: bool = False,
    rope: Optional[tuple] = None,
    attention_fn: AttentionFn = default_attention,
):
    """Multi-head attention over x [B, L, D] -> [B, L, D]."""
    b, l, _ = x.shape
    n_kv = n_kv_heads or n_heads
    dh = p["wq"].shape[1] // n_heads

    def proj(w, h):
        y = x @ w.astype(x.dtype)
        return y.reshape(b, l, h, dh).transpose(0, 2, 1, 3)  # [B, H, L, Dh]

    q, k, v = proj(p["wq"], n_heads), proj(p["wk"], n_kv), proj(p["wv"], n_kv)
    if rope is not None:
        cos, sin = rope
        q, k = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
    out = attention_fn(q, k, v, bias=bias, causal=causal)
    out = out.transpose(0, 2, 1, 3).reshape(b, l, n_heads * dh)
    return out @ p["wo"].astype(x.dtype)


# ---------------------------------------------------------------------------
# MLPs


def gelu_mlp_init(key, d_model, d_ff):
    k1, k2 = jax.random.split(key)
    return {
        "w1": dense_init(k1, d_model, d_ff),
        "b1": jnp.zeros((d_ff,), jnp.float32),
        "w2": dense_init(k2, d_ff, d_model),
        "b2": jnp.zeros((d_model,), jnp.float32),
    }


def gelu_mlp_apply(p, x):
    h = x @ p["w1"].astype(x.dtype) + p["b1"].astype(x.dtype)
    h = jax.nn.gelu(h)
    return h @ p["w2"].astype(x.dtype) + p["b2"].astype(x.dtype)


def swiglu_init(key, d_model, d_ff):
    kg, ku, kd = jax.random.split(key, 3)
    return {
        "w_gate": dense_init(kg, d_model, d_ff),
        "w_up": dense_init(ku, d_model, d_ff),
        "w_down": dense_init(kd, d_ff, d_model),
    }


def swiglu_apply(p, x):
    g = jax.nn.silu(x @ p["w_gate"].astype(x.dtype))
    u = x @ p["w_up"].astype(x.dtype)
    return (g * u) @ p["w_down"].astype(x.dtype)


# ---------------------------------------------------------------------------
# pre-LN encoder block (shared by BERT and ViT)


def prenorm_block_init(key, d_model, n_heads, d_ff):
    ka, km = jax.random.split(key)
    return {
        "ln1": ln_init(d_model),
        "attn": mha_init(ka, d_model, n_heads),
        "ln2": ln_init(d_model),
        "mlp": gelu_mlp_init(km, d_model, d_ff),
    }


def prenorm_block_apply(p, x, n_heads, bias=None,
                        attention_fn: AttentionFn = default_attention):
    x = x + mha_apply(p["attn"], layer_norm(x, p["ln1"]), n_heads,
                      bias=bias, attention_fn=attention_fn)
    return x + gelu_mlp_apply(p["mlp"], layer_norm(x, p["ln2"]))


# ---------------------------------------------------------------------------
# per-example LM loss (used by llama.py; here because it is model-generic)


def per_token_cross_entropy(logits, labels):
    """logits [B, L, V], labels int32 [B, L] -> fp32 [B, L]."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, labels[..., None], axis=-1).squeeze(-1)
    return logz - ll
