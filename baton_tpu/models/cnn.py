"""2-layer CNN for MNIST-shaped inputs (BASELINE config 1).

Written directly against ``jax.lax.conv_general_dilated`` (NHWC) so the
convs land on the MXU without framework overhead; params are a plain
dict pytree, vmappable over the client axis like every other model.
``conv_impl="im2col"`` switches to the patch-slices + batched-matmul
lowering shared with the ResNet (models/resnet.py::_conv_im2col) — the
MXU-friendly form for vmapped per-client training, where a direct conv
with batched weights lowers to a C-group grouped convolution.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from baton_tpu.core.losses import softmax_cross_entropy
from baton_tpu.core.model import FedModel
from baton_tpu.models.resnet import _CONV_IMPLS, _conv as _resnet_conv


def _conv(x, w, b, impl="direct"):
    return _resnet_conv(x, w, 1, impl) + b


def cnn_mnist_model(
    image_size: int = 28,
    channels: int = 1,
    n_classes: int = 10,
    width: int = 32,
    conv_impl: str = "direct",
    name: str = "cnn_mnist",
) -> FedModel:
    if conv_impl not in _CONV_IMPLS:
        raise ValueError(
            f"conv_impl must be one of {sorted(_CONV_IMPLS)}, got "
            f"{conv_impl!r}"
        )
    reduced = image_size // 4  # two 2x2 maxpools

    def init(rng):
        k1, k2, k3, k4 = jax.random.split(rng, 4)

        def he(key, shape, fan_in):
            return jax.random.normal(key, shape, jnp.float32) * jnp.sqrt(2.0 / fan_in)

        return {
            "conv1": {
                "w": he(k1, (3, 3, channels, width), 9 * channels),
                "b": jnp.zeros((width,), jnp.float32),
            },
            "conv2": {
                "w": he(k2, (3, 3, width, 2 * width), 9 * width),
                "b": jnp.zeros((2 * width,), jnp.float32),
            },
            "fc1": {
                "w": he(k3, (reduced * reduced * 2 * width, 128), reduced * reduced * 2 * width),
                "b": jnp.zeros((128,), jnp.float32),
            },
            "fc2": {
                "w": he(k4, (128, n_classes), 128),
                "b": jnp.zeros((n_classes,), jnp.float32),
            },
        }

    def apply(params, batch, rng):
        x = batch["x"]
        if x.ndim == 3:
            x = x[..., None]
        x = jax.nn.relu(_conv(x, params["conv1"]["w"], params["conv1"]["b"],
                              conv_impl))
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
        )
        x = jax.nn.relu(_conv(x, params["conv2"]["w"], params["conv2"]["b"],
                              conv_impl))
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
        )
        x = x.reshape(x.shape[0], -1)
        x = jax.nn.relu(x @ params["fc1"]["w"] + params["fc1"]["b"])
        return x @ params["fc2"]["w"] + params["fc2"]["b"]

    def per_example_loss(params, batch, rng):
        return softmax_cross_entropy(apply(params, batch, rng), batch, rng)

    return FedModel(init=init, apply=apply, per_example_loss=per_example_loss, name=name)
