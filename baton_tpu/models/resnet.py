"""ResNet-18 with GroupNorm — the flagship model (BASELINE config 2).

The reference framework never ships a real vision model (its demo model is
a 10->1 linear layer, reference demo.py:15-49); ResNet-18/CIFAR-10 is the
driver-set north-star workload. Design choices for TPU + federation:

* **GroupNorm, not BatchNorm**: BN running stats don't aggregate under
  client drift (see :meth:`baton_tpu.core.model.FedModel.from_flax`), and
  GN keeps the model a pure function of (params, batch) — vmappable over
  thousands of simulated clients with no mutable collections.
* **NHWC + optional bfloat16 compute**: convs lower to MXU-tiled
  ``conv_general_dilated``; params stay fp32 (FedAvg accumulates in
  fp32), activations/weights are cast to ``compute_dtype`` per-apply.
* **CIFAR stem** (3x3, stride 1, no maxpool) by default; ``imagenet_stem``
  switches to 7x7/stride-2 + maxpool for 224px inputs (ViT-sized runs).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from baton_tpu.core.losses import softmax_cross_entropy
from baton_tpu.core.model import FedModel

STAGE_WIDTHS: Tuple[int, ...] = (64, 128, 256, 512)
BLOCKS_PER_STAGE_18: Tuple[int, ...] = (2, 2, 2, 2)
BLOCKS_PER_STAGE_34: Tuple[int, ...] = (3, 4, 6, 3)


def _he(key, shape, fan_in):
    return jax.random.normal(key, shape, jnp.float32) * jnp.sqrt(2.0 / fan_in)


def _conv_init(key, kh, kw, cin, cout):
    return _he(key, (kh, kw, cin, cout), kh * kw * cin)


def _gn_init(c):
    return {"scale": jnp.ones((c,), jnp.float32), "bias": jnp.zeros((c,), jnp.float32)}


def _conv_direct(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x,
        w.astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _conv_im2col(x, w, stride=1):
    """SAME conv as (shifted slices -> concat) + one ``dot_general``.

    Why this exists: the flagship workload vmaps the model over a client
    axis with PER-CLIENT weights. ``vmap`` of ``conv_general_dilated``
    with a batched rhs lowers to a C-group grouped convolution, whose
    small per-group contractions leave the MXU mostly idle (measured
    ~8% MFU on v5e, TPU_EVIDENCE_r3.md). This formulation keeps every
    FLOP in a plain matmul: patch extraction is kh*kw strided slices
    (pure data movement, weight-independent — vmap leaves it untouched),
    and the contraction [B*OH*OW, kh*kw*Cin] x [kh*kw*Cin, Cout] becomes
    an MXU-tiled *batched* matmul under client-vmap. The kh*kw-fold
    activation blowup is transient (fused/freed by XLA) and is the price
    of dense MXU tiles.

    Numerics: identical contraction order per output element up to
    floating-point reassociation; tests pin it to the direct conv within
    dtype tolerance (tests/test_resnet.py).
    """
    kh, kw, cin, cout = w.shape
    cols = [xs for _, _, xs in _shifted_views(x, kh, kw, stride)]
    patches = jnp.concatenate(cols, axis=-1)  # [B, OH, OW, kh*kw*Cin]
    wm = w.astype(x.dtype).reshape(kh * kw * cin, cout)
    return jax.lax.dot_general(patches, wm, (((3,), (0,)), ((), ())))


def _shifted_views(x, kh, kw, stride):
    """Yield ``(i, j, shifted_view)`` for each kernel tap of a SAME
    conv: the strided slice of the padded input that tap (i, j)
    multiplies. Shared padding/slice arithmetic for the im2col and
    shift-GEMM lowerings."""
    b, h, wd, _ = x.shape
    oh = -(-h // stride)
    ow = -(-wd // stride)
    ph = max((oh - 1) * stride + kh - h, 0)
    pw = max((ow - 1) * stride + kw - wd, 0)
    xp = jnp.pad(
        x, ((0, 0), (ph // 2, ph - ph // 2), (pw // 2, pw - pw // 2), (0, 0))
    )
    for i in range(kh):
        for j in range(kw):
            yield i, j, xp[:, i : i + (oh - 1) * stride + 1 : stride,
                           j : j + (ow - 1) * stride + 1 : stride, :]


def _conv_shift(x, w, stride=1):
    """SAME conv as a sum of kh*kw shifted plain matmuls
    (``y = sum_ij shift(x, i, j) @ w[i, j]`` — the kn2row/shift-GEMM
    decomposition).

    Same motivation as :func:`_conv_im2col` (per-client weights under
    vmap must lower to batched matmuls, not C-group grouped
    convolutions) but WITHOUT im2col's kh*kw-fold patch
    materialization: each term reads a shifted view of ``x`` and
    contracts only over Cin, so peak activation HBM stays at the direct
    conv's level (the im2col wave-32 kernel's 19.2 GiB static plan
    exceeded the v5e's capacity — measured live, r4). The trade: kh*kw
    matmuls with K = Cin instead of one with K = kh*kw*Cin — smaller
    MXU tiles on the 64-channel stem, full-size from stage 2 on.

    Numerics: per output element the same multiply-adds as the direct
    conv, reassociated. The kh*kw partial products are accumulated in
    fp32 regardless of compute dtype (``preferred_element_type``) — a
    bf16 running sum would round at every inter-term add, drifting far
    past reassociation noise — and cast back once at return. Pinned
    against the direct conv in fp32 AND bf16 in tests/test_resnet.py.
    """
    kh, kw, _, _ = w.shape
    wm = w.astype(x.dtype)
    out = None
    for i, j, xs in _shifted_views(x, kh, kw, stride):
        term = jax.lax.dot_general(
            xs, wm[i, j], (((3,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        out = term if out is None else out + term
    return out.astype(x.dtype)


# module-level dispatch table so `conv_impl` stays a plain string in the
# model factory signature (hashable, serializable into configs)
_CONV_IMPLS = {"direct": _conv_direct, "im2col": _conv_im2col,
               "shift": _conv_shift}


def _conv(x, w, stride=1, impl="direct"):
    return _CONV_IMPLS[impl](x, w, stride)


def _group_norm(x, p, n_groups=32, eps=1e-5):
    """GroupNorm over NHWC; stats in fp32 regardless of compute dtype."""
    b, h, w, c = x.shape
    g = min(n_groups, c)
    xf = x.astype(jnp.float32).reshape(b, h, w, g, c // g)
    mean = jnp.mean(xf, axis=(1, 2, 4), keepdims=True)
    var = jnp.var(xf, axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + eps)
    xf = xf.reshape(b, h, w, c)
    return (xf * p["scale"] + p["bias"]).astype(x.dtype)


def _block_init(key, cin, cout, stride):
    k1, k2, k3 = jax.random.split(key, 3)
    p = {
        "conv1": _conv_init(k1, 3, 3, cin, cout),
        "gn1": _gn_init(cout),
        "conv2": _conv_init(k2, 3, 3, cout, cout),
        "gn2": _gn_init(cout),
    }
    if stride != 1 or cin != cout:
        p["proj"] = _conv_init(k3, 1, 1, cin, cout)
        p["gn_proj"] = _gn_init(cout)
    return p


def _block_apply(x, p, stride, n_groups, impl="direct"):
    out = _conv(x, p["conv1"], stride, impl)
    out = jax.nn.relu(_group_norm(out, p["gn1"], n_groups))
    out = _conv(out, p["conv2"], 1, impl)
    out = _group_norm(out, p["gn2"], n_groups)
    if "proj" in p:
        x = _group_norm(_conv(x, p["proj"], stride, impl), p["gn_proj"],
                        n_groups)
    return jax.nn.relu(out + x)


def resnet_model(
    blocks_per_stage: Sequence[int] = BLOCKS_PER_STAGE_18,
    n_classes: int = 10,
    channels: int = 3,
    n_groups: int = 32,
    width_multiplier: int = 1,
    imagenet_stem: bool = False,
    compute_dtype=jnp.float32,
    conv_impl: str = "direct",
    name: str = "resnet18",
) -> FedModel:
    if conv_impl not in _CONV_IMPLS:
        raise ValueError(
            f"conv_impl must be one of {sorted(_CONV_IMPLS)}, got "
            f"{conv_impl!r}"
        )
    if len(blocks_per_stage) > len(STAGE_WIDTHS):
        raise ValueError(
            f"at most {len(STAGE_WIDTHS)} stages supported, got "
            f"{len(blocks_per_stage)}"
        )
    widths = [w * width_multiplier for w in STAGE_WIDTHS]

    def stride_of(s, b):
        return 2 if (b == 0 and s > 0) else 1

    def init(rng):
        keys = jax.random.split(rng, 2 + sum(blocks_per_stage))
        it = iter(keys)
        stem_kh = 7 if imagenet_stem else 3
        params = {
            "stem": _conv_init(next(it), stem_kh, stem_kh, channels, widths[0]),
            "gn_stem": _gn_init(widths[0]),
        }
        cin = widths[0]
        for s, (n_blocks, cout) in enumerate(zip(blocks_per_stage, widths)):
            for b in range(n_blocks):
                params[f"s{s}b{b}"] = _block_init(
                    next(it), cin, cout, stride_of(s, b)
                )
                cin = cout
        params["fc"] = {
            "w": _he(next(it), (cin, n_classes), cin),
            "b": jnp.zeros((n_classes,), jnp.float32),
        }
        return params

    def apply(params, batch, rng):
        x = batch["x"].astype(compute_dtype)
        stem_stride = 2 if imagenet_stem else 1
        x = _conv(x, params["stem"], stem_stride, conv_impl)
        x = jax.nn.relu(_group_norm(x, params["gn_stem"], n_groups))
        if imagenet_stem:
            x = jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
            )
        for s, n_blocks in enumerate(blocks_per_stage):
            for b in range(n_blocks):
                x = _block_apply(x, params[f"s{s}b{b}"], stride_of(s, b),
                                 n_groups, conv_impl)
        x = jnp.mean(x, axis=(1, 2))
        logits = x.astype(jnp.float32) @ params["fc"]["w"] + params["fc"]["b"]
        return logits

    def per_example_loss(params, batch, rng):
        return softmax_cross_entropy(apply(params, batch, rng), batch, rng)

    return FedModel(init=init, apply=apply, per_example_loss=per_example_loss, name=name)


def resnet18_cifar_model(
    n_classes: int = 10, compute_dtype=jnp.float32, conv_impl: str = "direct",
    name: str = "resnet18_cifar"
) -> FedModel:
    """ResNet-18 for 32x32 inputs — the north-star/bench model.

    ``conv_impl="im2col"`` reformulates every conv as patch slices + a
    batched matmul — the MXU-friendly lowering for vmapped per-client
    training (see :func:`_conv_im2col`).
    """
    return resnet_model(
        BLOCKS_PER_STAGE_18,
        n_classes=n_classes,
        compute_dtype=compute_dtype,
        conv_impl=conv_impl,
        name=name,
    )
