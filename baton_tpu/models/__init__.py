from baton_tpu.models.linear import linear_regression_model
from baton_tpu.models.mlp import mlp_classifier_model
from baton_tpu.models.cnn import cnn_mnist_model
from baton_tpu.models.resnet import resnet_model, resnet18_cifar_model
from baton_tpu.models.lora import lora_wrap, lora_trainable, merge_lora
from baton_tpu.models.bert import BertConfig, bert_classifier_model
from baton_tpu.models.llama import LlamaConfig, llama_lm_model, llama_lora_target
from baton_tpu.models.lstm import LSTMConfig, lstm_lm_model
from baton_tpu.models.moe import MoEConfig, moe_apply, moe_init
from baton_tpu.models.vit import ViTConfig, vit_model

__all__ = [
    "linear_regression_model",
    "mlp_classifier_model",
    "cnn_mnist_model",
    "resnet_model",
    "resnet18_cifar_model",
    "lora_wrap",
    "lora_trainable",
    "merge_lora",
    "BertConfig",
    "bert_classifier_model",
    "LlamaConfig",
    "llama_lm_model",
    "llama_lora_target",
    "LSTMConfig",
    "lstm_lm_model",
    "MoEConfig",
    "moe_apply",
    "moe_init",
    "ViTConfig",
    "vit_model",
]
