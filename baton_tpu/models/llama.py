"""Llama-class decoder-only LM (BASELINE config 4: LoRA instruction-tune).

The reference has no language models (reference demo.py:15-49 is its whole
zoo); this decoder exists for the driver-set federated LoRA workload.
Architecture is the modern decoder recipe — RMSNorm pre-norm, RoPE,
SwiGLU MLP, grouped-query attention, untied output head — built from the
TPU-first blocks in :mod:`baton_tpu.models.transformer`:

* params fp32 / activations ``compute_dtype`` (bf16 on TPU), norms and
  softmax in fp32;
* causal masking is static inside the attention kernel; an optional
  per-token ``loss_mask`` weights the LM loss (instruction tuning
  masks the prompt);
* ``attention_fn`` is injectable — dense, fused-blockwise, or ring
  attention over a sequence mesh axis all fit behind the same signature;
* for federation, pair with :func:`baton_tpu.models.lora.lora_wrap` and
  ``trainable=lora_trainable`` so simulated clients carry only the
  adapter pytree (see :func:`llama_lora_target` for the standard
  attention-projection targeting).

Batches: ``{"x": int32[B, L] inputs, "y": int32[B, L] next-token targets,
"loss_mask"?: [B, L] 1.0 = token counts toward the loss}``. The
per-example loss is the per-sequence mean over unmasked tokens — [B],
as the framework contract requires (core/model.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from baton_tpu.core.model import FedModel
from baton_tpu.models.moe import MoEConfig, moe_apply, moe_init
from baton_tpu.models.transformer import (
    AttentionFn,
    dense_init,
    default_attention,
    mha_apply,
    mha_init,
    normal_init,
    per_token_cross_entropy,
    rms_init,
    rms_norm,
    rope_angles,
    swiglu_apply,
    swiglu_init,
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    max_len: int = 8192
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    rope_theta: float = 500000.0
    # Mixture-of-Experts: replaces every block's SwiGLU FFN with a
    # routed expert layer (models/moe.py) — the ep axis
    moe: Optional[MoEConfig] = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """Test-sized config (CI / CPU-mesh tests)."""
        defaults = dict(
            vocab_size=256, max_len=32, d_model=64, n_layers=2, n_heads=4,
            n_kv_heads=2, d_ff=128, rope_theta=10000.0,
        )
        defaults.update(kw)
        return cls(**defaults)


def llama_lora_target(path: str, leaf) -> bool:
    """LoRA target predicate: the attention projections (wq/wk/wv/wo) —
    the standard adapter placement for instruction tuning."""
    return path.rsplit("/", 1)[-1] in ("wq", "wk", "wv", "wo")


def _block_init(key, cfg: LlamaConfig):
    ka, km = jax.random.split(key)
    if cfg.moe is not None:
        mlp = moe_init(km, cfg.d_model, cfg.d_ff, cfg.moe)
    else:
        mlp = swiglu_init(km, cfg.d_model, cfg.d_ff)
    return {
        "norm_attn": rms_init(cfg.d_model),
        "attn": mha_init(
            ka, cfg.d_model, cfg.n_heads, n_kv_heads=cfg.n_kv_heads,
            out_std=cfg.d_model ** -0.5 / (2 * cfg.n_layers) ** 0.5,
        ),
        "norm_mlp": rms_init(cfg.d_model),
        "mlp": mlp,
    }


def _block_apply(p, x, cfg: LlamaConfig, rope, attention_fn: AttentionFn):
    """Returns (x, aux); aux is the block's MoE load-balance loss (0.0
    for dense blocks) — one output structure for both variants so the
    remat wrapper and the layer loop don't branch."""
    x = x + mha_apply(
        p["attn"], rms_norm(x, p["norm_attn"]), cfg.n_heads,
        n_kv_heads=cfg.n_kv_heads, causal=True, rope=rope,
        attention_fn=attention_fn,
    )
    h = rms_norm(x, p["norm_mlp"])
    if cfg.moe is not None:
        y, aux = moe_apply(p["mlp"], h, cfg.moe)
        return x + y, aux
    return x + swiglu_apply(p["mlp"], h), jnp.float32(0.0)


def llama_lm_model(
    config: Optional[LlamaConfig] = None,
    compute_dtype=jnp.float32,
    attention_fn: AttentionFn = default_attention,
    name: str = "llama_lm",
    remat: bool = False,
) -> FedModel:
    """``remat=True`` wraps each decoder block in ``jax.checkpoint``:
    the backward pass recomputes block activations instead of storing
    them, cutting activation memory from O(L·n_layers) to O(L) at ~1/3
    extra FLOPs — what makes long-sequence / large-model training
    (config 4) fit HBM."""
    cfg = config or LlamaConfig.llama3_8b()

    def init(rng):
        keys = jax.random.split(rng, cfg.n_layers + 2)
        return {
            "tok_emb": normal_init(keys[0], (cfg.vocab_size, cfg.d_model), 0.02),
            "blocks": [
                _block_init(keys[1 + i], cfg) for i in range(cfg.n_layers)
            ],
            "norm_f": rms_init(cfg.d_model),
            "lm_head": dense_init(keys[-1], cfg.d_model, cfg.vocab_size),
        }

    def _apply_with_aux(params, batch, rng):
        ids = batch["x"]
        l = ids.shape[1]
        rope = rope_angles(l, cfg.head_dim, cfg.rope_theta)
        x = params["tok_emb"][ids].astype(compute_dtype)
        block_fn = (
            jax.checkpoint(_block_apply, static_argnums=(2, 4))
            if remat
            else _block_apply
        )
        aux_total = jnp.float32(0.0)
        for blk in params["blocks"]:
            x, aux = block_fn(blk, x, cfg, rope, attention_fn)
            aux_total = aux_total + aux
        x = rms_norm(x, params["norm_f"])
        # bf16 operands, fp32 accumulation: the vocab projection is the
        # model's largest matmul — keep it on the fast MXU path
        logits = jax.lax.dot_general(
            x, params["lm_head"].astype(x.dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return logits, aux_total

    def apply(params, batch, rng):
        """Returns next-token logits [B, L, V] (fp32)."""
        return _apply_with_aux(params, batch, rng)[0]

    def _add_aux(per_example, aux):
        # the MoE load-balance penalty is a whole-forward scalar; add it
        # to EVERY example so the mean loss (what every consumer — the
        # trainer objective, DP-SGD's per-example path, the evaluator —
        # optimizes) gains exactly aux_weight·aux, independent of batch
        # size
        if cfg.moe is None:
            return per_example
        return per_example + cfg.moe.aux_weight * aux

    def per_example_loss(params, batch, rng):
        logits, aux = _apply_with_aux(params, batch, rng)
        tok_loss = per_token_cross_entropy(logits, batch["y"])  # [B, L]
        loss_mask = batch.get("loss_mask")
        if loss_mask is None:
            return _add_aux(jnp.mean(tok_loss, axis=-1), aux)
        m = loss_mask.astype(jnp.float32)
        loss = jnp.sum(tok_loss * m, axis=-1) / jnp.maximum(
            jnp.sum(m, axis=-1), 1.0
        )
        return _add_aux(loss, aux)

    return FedModel(init=init, apply=apply, per_example_loss=per_example_loss,
                    name=name, aux=cfg)
