"""Linear regression — the demo-parity model.

The reference demo model is a 10→1 ``nn.Linear`` trained with MSE + SGD
(reference: demo.py:15-49, name "lineartest" at demo.py:16). Here it is
a pure-functional FedModel: params are ``{"w": [d,1], "b": [1]}``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from baton_tpu.core.losses import mse
from baton_tpu.core.model import FedModel


def linear_regression_model(in_dim: int = 10, name: str = "lineartest") -> FedModel:
    def init(rng):
        wkey, _ = jax.random.split(rng)
        # Match torch.nn.Linear's default U(-1/sqrt(d), 1/sqrt(d)) scale.
        bound = 1.0 / jnp.sqrt(in_dim)
        return {
            "w": jax.random.uniform(wkey, (in_dim, 1), jnp.float32, -bound, bound),
            "b": jnp.zeros((1,), jnp.float32),
        }

    def apply(params, batch, rng):
        return batch["x"] @ params["w"] + params["b"]

    def per_example_loss(params, batch, rng):
        return mse(apply(params, batch, rng), batch, rng)

    return FedModel(init=init, apply=apply, per_example_loss=per_example_loss, name=name)
