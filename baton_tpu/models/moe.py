"""Mixture-of-Experts FFN with expert parallelism — the ``ep`` axis.

The reference has nothing remotely like this (its model zoo is a 10→1
linear layer, reference demo.py:15-49); this layer exists so the
decoder scales parameters past one chip the TPU way, completing the
framework's parallelism axes (dp=clients, tp=model, sp=seq, ep=experts).

TPU-first design:

* **Static shapes throughout** — top-k routing uses the GShard/Switch
  dispatch-tensor formulation: every expert gets a fixed capacity
  ``C = ceil(capacity_factor · K · L / E)`` and tokens beyond it are
  dropped (their gate mass is simply not added back — the residual
  stream carries them unchanged). No dynamic shapes, so the whole layer
  jits, vmaps over clients, and remats.
* **Everything is einsum** — dispatch [B,S,E,C] · tokens [B,S,D] feeds
  the stacked expert weights [E, D, F] in one batched contraction the
  MXU tiles; combine is the transpose einsum weighted by the gates.
  The dispatch tensor costs O(B·K·L·E·C) fp32 — fine for the
  federated/long-context regimes this zoo targets; for trillion-scale
  routing you would move to ragged all-to-all dispatch.
* **Expert parallelism is a sharding annotation, not collectives** —
  the stacked expert dim E is sharded over the ``model`` mesh axis
  (parallel/tensor_parallel.py rules); GSPMD partitions the expert
  einsums and inserts the all-to-alls. The router stays replicated.
* **Load-balance aux loss** (Switch Transformer): ``E · Σ_e f_e · P_e``
  where f_e is the fraction of tokens whose top-1 choice is e and P_e
  the mean router probability — minimized (=1) at uniform routing.
  :func:`baton_tpu.models.llama.llama_lm_model` folds it into the
  per-example loss with ``moe.aux_weight``.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from baton_tpu.models.transformer import dense_init


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_weight: float = 0.01


def moe_init(key, d_model: int, d_ff: int, cfg: MoEConfig):
    kr, kg, ku, kd = jax.random.split(key, 4)
    e = cfg.n_experts

    def stack(k, d_in, d_out):
        return jax.vmap(lambda kk: dense_init(kk, d_in, d_out))(
            jax.random.split(k, e)
        )

    return {
        "router": dense_init(kr, d_model, e),
        "w_gate": stack(kg, d_model, d_ff),   # [E, D, F]
        "w_up": stack(ku, d_model, d_ff),     # [E, D, F]
        "w_down": stack(kd, d_ff, d_model),   # [E, F, D]
    }


def moe_capacity(cfg: MoEConfig, seq_len: int) -> int:
    return max(
        1, math.ceil(cfg.capacity_factor * cfg.top_k * seq_len / cfg.n_experts)
    )


def moe_apply(p, x, cfg: MoEConfig):
    """x [B, L, D] -> (y [B, L, D] in x.dtype, aux fp32 scalar).

    Routing math is fp32 regardless of compute dtype; the expert
    matmuls keep x's dtype with fp32 accumulation (MXU bf16 path).
    """
    b, l, d = x.shape
    e, k = cfg.n_experts, cfg.top_k
    c = moe_capacity(cfg, l)

    logits = jnp.einsum(
        "bld,de->ble", x.astype(jnp.float32), p["router"]
    )
    probs = jax.nn.softmax(logits, axis=-1)                  # [B, L, E]
    gate, idx = jax.lax.top_k(probs, k)                      # [B, L, K]
    gate = gate / jnp.maximum(gate.sum(-1, keepdims=True), 1e-9)

    # flatten choices k-major (s = k·L + l): every token's 1st choice
    # claims capacity before any token's 2nd choice — the Switch/GShard
    # priority order
    idx_f = jnp.swapaxes(idx, 1, 2).reshape(b, k * l)        # [B, S]
    gate_f = jnp.swapaxes(gate, 1, 2).reshape(b, k * l)
    mask = jax.nn.one_hot(idx_f, e, dtype=jnp.float32)       # [B, S, E]
    pos = jnp.sum(
        (jnp.cumsum(mask, axis=1) - 1.0) * mask, axis=-1
    ).astype(jnp.int32)
    # over-capacity slots (pos >= C) one_hot to an all-zero row — the
    # token is dropped with no extra masking needed
    disp = (
        mask[..., None]
        * jax.nn.one_hot(pos, c, dtype=jnp.float32)[:, :, None, :]
    )                                                        # [B, S, E, C]

    # expose the k axis on the dispatch tensor instead of materializing
    # k copies of x (s = k·L + l is k-major, so the reshape is exact)
    disp_x = disp.astype(x.dtype)
    expert_in = jnp.einsum(
        "bklec,bld->becd", disp_x.reshape(b, k, l, e, c), x
    )                                                        # [B, E, C, D]
    h_gate = jnp.einsum(
        "becd,edf->becf", expert_in, p["w_gate"].astype(x.dtype),
        preferred_element_type=jnp.float32,
    )
    h_up = jnp.einsum(
        "becd,edf->becf", expert_in, p["w_up"].astype(x.dtype),
        preferred_element_type=jnp.float32,
    )
    h = (jax.nn.silu(h_gate) * h_up).astype(x.dtype)
    expert_out = jnp.einsum(
        "becf,efd->becd", h, p["w_down"].astype(x.dtype),
        preferred_element_type=jnp.float32,
    )                                                        # fp32

    comb = disp * gate_f[..., None, None]                    # [B, S, E, C]
    y = jnp.einsum("bsec,becd->bsd", comb, expert_out)       # fp32 [B, S, D]
    y = y.reshape(b, k, l, d).sum(axis=1)                    # fold choices

    # Switch load-balance aux over top-1 assignments
    top1 = jax.nn.one_hot(idx[..., 0], e, dtype=jnp.float32)
    frac_tokens = jnp.mean(top1, axis=(0, 1))                # [E]
    mean_prob = jnp.mean(probs, axis=(0, 1))                 # [E]
    aux = e * jnp.sum(frac_tokens * mean_prob)
    return y.astype(x.dtype), aux


def moe_dense_oracle(p, x, cfg: MoEConfig):
    """Reference implementation with NO capacity dropping: every token
    is processed by its top-k experts densely — what :func:`moe_apply`
    must equal whenever capacity is ample (tests)."""
    probs = jax.nn.softmax(
        jnp.einsum("bld,de->ble", x.astype(jnp.float32), p["router"]),
        axis=-1,
    )
    gate, idx = jax.lax.top_k(probs, cfg.top_k)
    gate = gate / jnp.maximum(gate.sum(-1, keepdims=True), 1e-9)

    def ffn(xe, e):
        g = jax.nn.silu(
            xe.astype(jnp.float32) @ p["w_gate"][e].astype(jnp.float32)
        )
        u = xe.astype(jnp.float32) @ p["w_up"][e].astype(jnp.float32)
        return (g * u) @ p["w_down"][e].astype(jnp.float32)

    all_out = jnp.stack(
        [ffn(x, e) for e in range(cfg.n_experts)], axis=2
    )  # [B, L, E, D]
    sel = jnp.take_along_axis(
        all_out, idx[..., None], axis=2
    )  # [B, L, K, D]
    return jnp.sum(sel * gate[..., None], axis=2).astype(x.dtype)
