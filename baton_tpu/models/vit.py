"""Vision Transformer (BASELINE config 5: ViT-B/16 DP cross-silo).

The reference's only model is a linear regressor (reference
demo.py:15-49); ViT exists for the driver-set differential-privacy
cross-silo workload. TPU-first construction on the shared blocks of
:mod:`baton_tpu.models.transformer`:

* **Patchify is one matmul**: [B, H, W, C] -> [B, N, P*P*C] by reshape/
  transpose, then a dense projection — identical math to the usual
  stride-P conv, but explicitly the shape XLA tiles best on the MXU.
* Pre-LN encoder blocks, GELU MLP, learned position embeddings, class
  token, fp32 norms/softmax over ``compute_dtype`` activations.
* No BatchNorm anywhere (pure function of params — vmappable over the
  client axis; cf. core/model.py on the federated BN problem).

Batches: ``{"x": f32[B, H, W, C], "y": int32[B]}`` with H, W divisible by
``patch``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from baton_tpu.core.losses import softmax_cross_entropy
from baton_tpu.core.model import FedModel
from baton_tpu.models.transformer import (
    AttentionFn,
    dense_init,
    default_attention,
    layer_norm,
    ln_init,
    normal_init,
    prenorm_block_apply,
    prenorm_block_init,
)


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch: int = 16
    channels: int = 3
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    n_classes: int = 1000

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch) ** 2

    @classmethod
    def b16(cls, **kw) -> "ViTConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "ViTConfig":
        """Test-sized config (CI / CPU-mesh tests)."""
        defaults = dict(
            image_size=16, patch=4, channels=3, d_model=32, n_layers=2,
            n_heads=4, d_ff=64, n_classes=10,
        )
        defaults.update(kw)
        return cls(**defaults)


def _patchify(x, patch):
    """[B, H, W, C] -> [B, N, patch*patch*C] without convolution."""
    b, h, w, c = x.shape
    gh, gw = h // patch, w // patch
    x = x.reshape(b, gh, patch, gw, patch, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, gh * gw, patch * patch * c)


def vit_model(
    config: Optional[ViTConfig] = None,
    compute_dtype=jnp.float32,
    attention_fn: AttentionFn = default_attention,
    name: str = "vit",
    remat: bool = False,
) -> FedModel:
    """``remat=True`` wraps each encoder block in ``jax.checkpoint`` —
    recompute-not-store for block activations, mirroring
    models/llama.py::llama_lm_model. The DP cross-silo workload
    (config 5) holds per-example grads for clipping, so activation HBM
    is the binding constraint remat relieves."""
    cfg = config or ViTConfig.b16()
    patch_dim = cfg.patch * cfg.patch * cfg.channels

    def init(rng):
        keys = jax.random.split(rng, cfg.n_layers + 4)
        return {
            "patch_proj": {
                "w": dense_init(keys[0], patch_dim, cfg.d_model),
                "b": jnp.zeros((cfg.d_model,), jnp.float32),
            },
            "cls_token": normal_init(keys[1], (1, 1, cfg.d_model), 0.02),
            "pos_emb": normal_init(
                keys[2], (cfg.n_patches + 1, cfg.d_model), 0.02
            ),
            "blocks": [
                prenorm_block_init(keys[3 + i], cfg.d_model, cfg.n_heads, cfg.d_ff)
                for i in range(cfg.n_layers)
            ],
            "ln_f": ln_init(cfg.d_model),
            "head": {
                "w": dense_init(keys[-1], cfg.d_model, cfg.n_classes),
                "b": jnp.zeros((cfg.n_classes,), jnp.float32),
            },
        }

    def apply(params, batch, rng):
        x = _patchify(batch["x"], cfg.patch).astype(compute_dtype)
        x = x @ params["patch_proj"]["w"].astype(x.dtype) + params[
            "patch_proj"
        ]["b"].astype(x.dtype)
        b = x.shape[0]
        cls = jnp.broadcast_to(
            params["cls_token"].astype(x.dtype), (b, 1, cfg.d_model)
        )
        x = jnp.concatenate([cls, x], axis=1) + params["pos_emb"].astype(x.dtype)
        def _block(blk, x):
            return prenorm_block_apply(blk, x, cfg.n_heads,
                                       attention_fn=attention_fn)

        block_fn = jax.checkpoint(_block) if remat else _block
        for blk in params["blocks"]:
            x = block_fn(blk, x)
        x = layer_norm(x, params["ln_f"])
        cls_out = x[:, 0, :].astype(jnp.float32)
        return cls_out @ params["head"]["w"] + params["head"]["b"]

    def per_example_loss(params, batch, rng):
        return softmax_cross_entropy(apply(params, batch, rng), batch, rng)

    return FedModel(init=init, apply=apply, per_example_loss=per_example_loss,
                    name=name, aux=cfg)
