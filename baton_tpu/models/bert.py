"""BERT-style text encoder/classifier (BASELINE config 3: AG-News FedProx).

The reference has no NLP models at all (its model zoo is one linear layer,
reference demo.py:15-49); this encoder exists for the driver-set federated
fine-tune workloads. TPU-first choices:

* **Pre-LN** blocks (norm before attn/MLP) + a final LayerNorm: unlike
  the original post-LN BERT this trains stably without LR warmup games —
  important when thousands of simulated clients each run short local
  schedules from a common init.
* Learned absolute position embeddings, single segment (no token-type
  table; AG-News classification is single-sequence).
* First-token ("[CLS]") pooling through a tanh pooler head.
* Padding handled as an additive attention bias built from
  ``batch["attn_mask"]`` ([B, L], 1 = real token); absent mask = all real.

Batches: ``{"x": int32[B, L], "attn_mask"?: [B, L], "y": int32[B]}``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from baton_tpu.core.losses import softmax_cross_entropy
from baton_tpu.core.model import FedModel
from baton_tpu.models.transformer import (
    AttentionFn,
    dense_init,
    default_attention,
    layer_norm,
    ln_init,
    normal_init,
    padding_bias,
    prenorm_block_apply,
    prenorm_block_init,
)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_len: int = 128
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    n_classes: int = 4  # AG-News

    @classmethod
    def base(cls, **kw) -> "BertConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "BertConfig":
        """Test-sized config (CI / CPU-mesh tests)."""
        defaults = dict(
            vocab_size=128, max_len=16, d_model=32, n_layers=2, n_heads=4,
            d_ff=64, n_classes=4,
        )
        defaults.update(kw)
        return cls(**defaults)


def bert_classifier_model(
    config: Optional[BertConfig] = None,
    compute_dtype=jnp.float32,
    attention_fn: AttentionFn = default_attention,
    name: str = "bert_classifier",
    remat: bool = False,
) -> FedModel:
    """``remat=True`` wraps each encoder block in ``jax.checkpoint`` —
    the backward pass recomputes block activations instead of storing
    them, the same HBM/FLOPs trade the Llama decoder makes
    (models/llama.py::llama_lm_model). Long-sequence FedProx fine-tunes
    (config 3) use it to fit larger cohorts per wave."""
    cfg = config or BertConfig.base()

    def init(rng):
        keys = jax.random.split(rng, cfg.n_layers + 4)
        params = {
            "tok_emb": normal_init(keys[0], (cfg.vocab_size, cfg.d_model), 0.02),
            "pos_emb": normal_init(keys[1], (cfg.max_len, cfg.d_model), 0.02),
            "blocks": [
                prenorm_block_init(keys[2 + i], cfg.d_model, cfg.n_heads, cfg.d_ff)
                for i in range(cfg.n_layers)
            ],
            "ln_f": ln_init(cfg.d_model),
            "pooler": {
                "w": dense_init(keys[-2], cfg.d_model, cfg.d_model),
                "b": jnp.zeros((cfg.d_model,), jnp.float32),
            },
            "head": {
                "w": dense_init(keys[-1], cfg.d_model, cfg.n_classes),
                "b": jnp.zeros((cfg.n_classes,), jnp.float32),
            },
        }
        return params

    def apply(params, batch, rng):
        ids = batch["x"]
        b, l = ids.shape
        x = params["tok_emb"][ids] + params["pos_emb"][:l]
        x = x.astype(compute_dtype)
        attn_mask = batch.get("attn_mask")
        bias = None if attn_mask is None else padding_bias(attn_mask)

        def _block(blk, x, bias):
            return prenorm_block_apply(blk, x, cfg.n_heads, bias=bias,
                                       attention_fn=attention_fn)

        block_fn = jax.checkpoint(_block) if remat else _block
        for blk in params["blocks"]:
            x = block_fn(blk, x, bias)
        x = layer_norm(x, params["ln_f"])
        cls = x[:, 0, :].astype(jnp.float32)
        pooled = jnp.tanh(cls @ params["pooler"]["w"] + params["pooler"]["b"])
        return pooled @ params["head"]["w"] + params["head"]["b"]

    def per_example_loss(params, batch, rng):
        return softmax_cross_entropy(apply(params, batch, rng), batch, rng)

    return FedModel(init=init, apply=apply, per_example_loss=per_example_loss,
                    name=name, aux=cfg)
