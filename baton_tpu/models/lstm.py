"""Character-level LSTM language model (the FedAvg-paper Shakespeare
workload).

The reference's model zoo is a single linear regressor (reference
demo.py:15-49); this model covers the *canonical* federated-learning
benchmark family the original FedAvg paper established — a stacked
character LSTM where each client is one Shakespeare speaking role — so
users of classic FL baselines find their workload here.

TPU-first construction:

* The recurrence is a single ``lax.scan`` over time carrying ``(h, c)``
  for all layers — one compiled loop, no Python timestep unrolling, and
  the whole multi-epoch local-training run still fuses into the
  framework's scan-of-scans (core/training.py).
* Each step's gate computation is ONE ``[B, E+H] @ [E+H, 4H]`` matmul
  per layer (inputs and hidden concatenated, all four gates fused), the
  layout XLA tiles best on the MXU — not four separate small matmuls.
* Params are fp32; activations run in ``compute_dtype`` with the cell
  state kept fp32 (the additive ``c`` path is where bf16 error
  accumulates over long sequences); gate nonlinearities in fp32.
* Forget-gate bias initialized to 1.0 (the standard trick so gradients
  flow through the cell path at init).

Batches: ``{"x": int32[B, L] chars, "y": int32[B, L] next chars,
"loss_mask"?: [B, L]}`` — the same contract as the decoder LM
(models/llama.py), so partitioners/recipes compose unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from baton_tpu.core.model import FedModel
from baton_tpu.models.transformer import (
    dense_init,
    normal_init,
    per_token_cross_entropy,
)


@dataclasses.dataclass(frozen=True)
class LSTMConfig:
    vocab_size: int = 90      # printable-ASCII Shakespeare alphabet
    d_embed: int = 8          # FedAvg-paper char embedding is tiny
    d_hidden: int = 256
    n_layers: int = 2

    @classmethod
    def shakespeare(cls, **kw) -> "LSTMConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "LSTMConfig":
        """Test-sized config (CI / CPU-mesh tests)."""
        defaults = dict(vocab_size=32, d_embed=4, d_hidden=16, n_layers=2)
        defaults.update(kw)
        return cls(**defaults)


def _cell_init(key, d_in: int, d_hidden: int):
    # one fused kernel for all four gates: [d_in + d_hidden, 4*d_hidden]
    bias = jnp.zeros((4 * d_hidden,), jnp.float32)
    bias = bias.at[d_hidden:2 * d_hidden].set(1.0)  # forget gate
    return {
        "kernel": dense_init(key, d_in + d_hidden, 4 * d_hidden),
        "bias": bias,
    }


def _cell_step(p, x, h, c, compute_dtype):
    """One LSTM step: x [B, d_in], h [B, H], c fp32 [B, H]."""
    z = jnp.concatenate([x, h], axis=-1) @ p["kernel"].astype(x.dtype)
    z = z.astype(jnp.float32) + p["bias"]
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = (jax.nn.sigmoid(o) * jnp.tanh(c)).astype(compute_dtype)
    return h, c


def lstm_lm_model(
    config: Optional[LSTMConfig] = None,
    compute_dtype=jnp.float32,
    name: str = "lstm_lm",
) -> FedModel:
    cfg = config or LSTMConfig.shakespeare()

    def init(rng):
        keys = jax.random.split(rng, cfg.n_layers + 2)
        layers = []
        d_in = cfg.d_embed
        for i in range(cfg.n_layers):
            layers.append(_cell_init(keys[1 + i], d_in, cfg.d_hidden))
            d_in = cfg.d_hidden
        return {
            "embed": normal_init(keys[0], (cfg.vocab_size, cfg.d_embed), 0.1),
            "layers": layers,
            "out": dense_init(keys[-1], cfg.d_hidden, cfg.vocab_size),
        }

    def apply(params, batch, rng):
        """Next-char logits fp32 [B, L, V]."""
        ids = batch["x"]
        b, l = ids.shape
        x = params["embed"][ids].astype(compute_dtype)  # [B, L, E]

        h0 = jnp.zeros((cfg.n_layers, b, cfg.d_hidden), compute_dtype)
        c0 = jnp.zeros((cfg.n_layers, b, cfg.d_hidden), jnp.float32)

        def step(carry, x_t):
            h, c = carry
            inp = x_t
            hs, cs = [], []
            for i, layer in enumerate(params["layers"]):
                h_i, c_i = _cell_step(layer, inp, h[i], c[i], compute_dtype)
                hs.append(h_i)
                cs.append(c_i)
                inp = h_i
            return (jnp.stack(hs), jnp.stack(cs)), inp

        # scan over time: xs [L, B, E] -> top-layer hiddens [L, B, H]
        _, top = jax.lax.scan(step, (h0, c0), x.swapaxes(0, 1))
        top = top.swapaxes(0, 1)  # [B, L, H]
        return jax.lax.dot_general(
            top, params["out"].astype(top.dtype),
            (((top.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def per_example_loss(params, batch, rng):
        tok_loss = per_token_cross_entropy(apply(params, batch, rng),
                                           batch["y"])  # [B, L]
        loss_mask = batch.get("loss_mask")
        if loss_mask is None:
            return jnp.mean(tok_loss, axis=-1)
        m = loss_mask.astype(jnp.float32)
        return jnp.sum(tok_loss * m, axis=-1) / jnp.maximum(
            jnp.sum(m, axis=-1), 1.0
        )

    return FedModel(init=init, apply=apply, per_example_loss=per_example_loss,
                    name=name, aux=cfg)
