"""LoRA — low-rank adapter fine-tuning for federated models.

BASELINE config 4 (Llama-class LoRA federated instruction-tune): clients
train and ship only rank-r adapter factors; the base model is frozen and
replicated once. In the reference's architecture this would still ship
the full state_dict every round (manager.py:77-86); here the adapter-only
payload composes with :class:`baton_tpu.core.partition.ParamPartition` so
the per-client vmap axis carries just the adapters — the difference
between C×8B and C×a-few-MB of HBM.

Parameter-space formulation: for every targeted 2-D weight ``W [in,out]``
the effective weight is ``W + (alpha/rank)·A@B`` with ``A [in,r]`` normal
/ ``B [r,out]`` zeros (so step 0 is exactly the base model). The wrapped
model's params are ``{"base": ..., "lora": {path: {"a","b"}}}`` and
``apply`` merges on the fly — any model whose hot weights are 2-D matmul
leaves gets LoRA without modifying its code.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from baton_tpu.core.model import FedModel
from baton_tpu.core.partition import path_str

TargetPredicate = Callable[[str, Any], bool]


@dataclasses.dataclass(frozen=True)
class LoraSpec:
    """Rank/alpha of a wrapped model, stored on ``FedModel.aux`` so the
    training-time scale and the deploy-time merge cannot diverge."""

    rank: int
    alpha: float

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def default_target(path: str, leaf) -> bool:
    """Adapt every 2-D matrix leaf (matmul weights; biases/norms are 1-D)."""
    return hasattr(leaf, "ndim") and leaf.ndim == 2


def lora_trainable(path: str, leaf) -> bool:
    """Partition predicate selecting adapter leaves of a wrapped model."""
    return path.startswith("lora/")


def _lora_paths(base_params, target: TargetPredicate):
    path_leaves, _ = jax.tree_util.tree_flatten_with_path(base_params)
    return [
        (path_str(p), l.shape) for p, l in path_leaves if target(path_str(p), l)
    ]


def merge_lora_model(model: FedModel, params):
    """Materialize deploy params for a :func:`lora_wrap`-ped model, using
    the exact scale it was trained with (``model.aux``)."""
    spec = model.aux
    if not isinstance(spec, LoraSpec):
        raise ValueError(f"{model.name} is not a lora_wrap-ped model")
    return merge_lora(params, spec.alpha, spec.rank)


def merge_lora(params, alpha: float, rank: int):
    """Materialize effective base params: ``W += (alpha/rank)·A@B``.

    Prefer :func:`merge_lora_model`, which cannot drift from the
    training-time scale."""
    scale = alpha / rank
    lora = params["lora"]
    path_leaves, treedef = jax.tree_util.tree_flatten_with_path(params["base"])
    merged = []
    for p, leaf in path_leaves:
        key = path_str(p)
        if key in lora:
            ab = lora[key]["a"] @ lora[key]["b"]
            leaf = leaf + (scale * ab).astype(leaf.dtype)
        merged.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, merged)


def lora_wrap(
    model: FedModel,
    rank: int = 8,
    alpha: Optional[float] = None,
    target: TargetPredicate = default_target,
    name: Optional[str] = None,
) -> FedModel:
    """Wrap ``model`` with LoRA adapters on every targeted 2-D weight.

    Use with ``FedSim(..., trainable=lora_trainable)`` so only adapters
    are per-client/aggregated. ``model.init`` supplies the base weights;
    load pretrained weights by overwriting ``params["base"]`` after init.
    """
    if alpha is None:
        alpha = 2.0 * rank
    spec = LoraSpec(rank=rank, alpha=float(alpha))

    def init(rng):
        base_rng, lora_rng = jax.random.split(rng)
        base = model.init(base_rng)
        specs = _lora_paths(base, target)
        if not specs:
            raise ValueError("LoRA target predicate matched no 2-D leaves")
        keys = jax.random.split(lora_rng, len(specs))
        adapters = {}
        for k, (path, shape) in zip(keys, specs):
            fan_in, fan_out = shape
            adapters[path] = {
                "a": jax.random.normal(k, (fan_in, rank), jnp.float32)
                / jnp.sqrt(fan_in),
                "b": jnp.zeros((rank, fan_out), jnp.float32),
            }
        return {"base": base, "lora": adapters}

    def apply(params, batch, rng):
        return model.apply(merge_lora(params, alpha, rank), batch, rng)

    def per_example_loss(params, batch, rng):
        return model.per_example_loss(merge_lora(params, alpha, rank), batch, rng)

    return FedModel(
        init=init,
        apply=apply,
        per_example_loss=per_example_loss,
        name=name or f"{model.name}_lora{rank}",
        aux=spec,
    )
