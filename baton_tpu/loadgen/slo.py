"""SLO evaluator + regression gate over a scenario run's telemetry.

Inputs are exactly what the manager already records — the per-round SLO
records in ``rounds.jsonl`` (tolerantly read: a torn final line from a
crash is counted and reported, never raised) and the
``Experiment.metrics_snapshot()`` dict that also backs ``GET /metrics``
— plus the loadgen driver's own counters. From those it derives one
flat ``{metric_name: float}`` namespace:

``rounds.*``
    Derived from ``rounds.jsonl``: ``total`` / ``completed`` /
    ``aborted`` / ``completion_rate``, exact quantiles
    ``duration_p50|p95|p99`` + ``duration_mean|max`` over *completed*
    rounds, ``participants_mean`` / ``reporters_mean`` /
    ``straggler_rate``, and per-round byte means
    ``bytes_uploaded_mean`` / ``bytes_broadcast_mean``.
``counter:<name>`` / ``gauge:<name>``
    Straight from the manager snapshot.
``timer:<name>:<stat>``
    Histogram timer stats, ``<stat>`` in ``count`` / ``mean`` / ``p50``
    / ``p95`` / ``p99`` / ``max`` (e.g. ``timer:round_s:p95``).
``fleet:counter:<name>`` / ``fleet:gauge:<name>`` / ``fleet:timer:…``
    The worker fleet's shared registry (the engine points every
    simulated worker at one Metrics instance), e.g.
    ``fleet:timer:heartbeat_s:p95``.
``loadgen:<name>``
    The scenario driver's own counters/gauges (423 refusals, churn
    events, forced round ends).
``history:samples`` / ``history:span_s`` / ``history:delta:<counter>``
    / ``history:rate:<counter>``
    Derived from the manager's ``/metrics/history`` snapshot ring
    (``metrics_history.json``): windowed counter deltas over the run
    and per-second rates over the ring's wall-clock span. These are
    NOT absence-is-zero — a run that produced no history ring (or too
    few samples for a rate) fails the assertion, same rule as timers.
``alert:*``
    Derived from the manager's ``alerts.jsonl`` lifecycle stream
    (``baton_tpu.obs.alerts``): ``alert:fired:<rule>`` /
    ``alert:resolved:<rule>`` count one rule's firing/resolved
    transitions, ``alert:fired_total`` / ``alert:pages_fired`` sum
    across rules, and ``alert:forensics_bundles`` counts the forensics
    bundles captures actually produced. These are absence-is-zero like
    counters — "the run fired no alerts" is a real, assertable zero
    (``{"metric": "alert:fired_total", "op": "==", "value": 0}`` is the
    quiet-fleet gate).
``runbook:*``
    Derived from the manager's ``runbooks.jsonl`` lifecycle stream and
    the per-round ``actuations`` records
    (``baton_tpu.obs.runbooks``): ``runbook:entered:<rule>`` /
    ``runbook:exited:<rule>`` transition counts (exited ≥ 1 is the
    hysteresis-reversal proof), ``runbook:entered_total`` /
    ``runbook:exited_total``, ``runbook:actuated_rounds:<action>``, and
    ``runbook:actuations_total``. Absence-is-zero like counters.
``fairness:*``
    Per-class participation shares from ``fleet_health.json`` —
    ``fairness:share:<class>``, ``fairness:share_per_client:<class>``,
    ``fairness:clients:<class>``, ``fairness:participation_floor``
    (see :func:`derive_fairness_metrics`). NOT absence-is-zero: the
    starvation gate must fail loudly if fairness went unmeasured.
``compute:*``
    Derived from the ``compute`` section the manager folds into every
    round record (obs/compute.py): ``rounds_with_compute``,
    ``reporters_mean``, ``compile_s_max|mean``, ``steps_total``,
    ``samples_per_sec_per_chip_mean``, ``mfu_mean``,
    ``peak_hbm_gb_max``, ``recompile_storm_rounds``. A compute value
    that is null *with a recorded reason* in every round (CPU smoke has
    no MFU) becomes a ``skips`` entry instead of a metric — the
    baseline gate reports it ``skipped`` rather than regressed, exactly
    the bench carve-out; a null with NO reason is simply absent and
    regresses.

A *counter* address that the run never touched resolves to 0 — a
counter is born at its first ``inc``, so absence IS zero
(``counter:…``, ``fleet:counter:…``, and the ``loadgen:…`` namespace).
Every other address — timers, gauges, derived ``rounds.*`` — stays
missing when unproduced, and missing is a failure: "we stopped
measuring it" is precisely the regression class that hid the BENCH_r04
``fused_rounds_per_sec`` drop.

Two gates run over that namespace, both recorded in ``slo_report.json``:

1. **Assertions** from the scenario's ``slo.assertions`` block —
   ``{"metric", "op", "value"}``; an unresolvable metric is a *failure*
   (status ``missing``), per the absence rule above.
2. **Baseline deltas** vs a committed ``benchmarks/scenarios/baselines/
   *.json`` file: each entry pins ``value``, a ``direction``
   (``higher_is_better`` / ``lower_is_better``) and a relative
   ``tolerance`` (plus optional absolute ``tolerance_abs``); an
   observation worse than ``value ± tolerance`` — or missing from the
   run (counter addresses excepted, see above) — is a regression.

``evaluate_slo`` returns the full report; ``report["pass"]`` is the CI
verdict (any failed/missing assertion or any baseline regression ⇒
``False``, and the CLI exits nonzero).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence

from baton_tpu.loadgen.scenario import (
    SLO_OPS,
    ScenarioError,
    SLOAssertion,
    SLOSpec,
)

_TIMER_STATS = {
    "count": "count",
    "mean": "mean_s",
    "p50": "p50_s",
    "p95": "p95_s",
    "p99": "p99_s",
    "max": "max_s",
}

_DIRECTIONS = ("higher_is_better", "lower_is_better")


def _count(v: Any) -> int:
    """Record fields that enumerate clients (``stragglers``) hold id
    lists; count-valued fields hold numbers. Normalize either to an
    int."""
    if isinstance(v, (list, tuple)):
        return len(v)
    if isinstance(v, (int, float)):
        return int(v)
    return 0


def _quantile(sorted_vals: Sequence[float], q: float) -> float:
    """Exact linear-interpolation quantile over a sorted sample (the
    rounds sample is small, unlike the manager's O(1) histograms)."""
    n = len(sorted_vals)
    if n == 1:
        return sorted_vals[0]
    rank = q * (n - 1)
    lo = int(rank)
    hi = min(lo + 1, n - 1)
    frac = rank - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def resolve_metric(metrics: Dict[str, float], name: str) -> Optional[float]:
    """Metric lookup with the counter absence-is-zero rule (module
    docstring): an untouched counter address resolves to 0.0, anything
    else absent resolves to None (→ missing/regression)."""
    val = metrics.get(name)
    if val is not None:
        return val
    if name.startswith(("counter:", "fleet:counter:", "edge:counter:",
                        "loadgen:", "alert:", "runbook:")):
        return 0.0
    return None


def derive_metrics(
    records: List[dict],
    snapshot: Optional[dict] = None,
    loadgen_snapshot: Optional[dict] = None,
    fleet_snapshot: Optional[dict] = None,
    edge_snapshot: Optional[dict] = None,
) -> Dict[str, float]:
    """Flatten rounds.jsonl + the metrics snapshots into one
    ``{metric: float}`` namespace (see module docstring). Metrics whose
    inputs are absent (no completed rounds → no duration quantiles) are
    simply not present — the assertion layer turns absence into
    failure."""
    m: Dict[str, float] = {}
    total = len(records)
    completed = [r for r in records if r.get("outcome") == "completed"]
    m["rounds.total"] = float(total)
    m["rounds.completed"] = float(len(completed))
    m["rounds.aborted"] = float(total - len(completed))
    if total:
        m["rounds.completion_rate"] = len(completed) / total

    durs = sorted(
        float(r["duration_s"]) for r in completed
        if isinstance(r.get("duration_s"), (int, float))
    )
    if durs:
        m["rounds.duration_p50"] = _quantile(durs, 0.50)
        m["rounds.duration_p95"] = _quantile(durs, 0.95)
        m["rounds.duration_p99"] = _quantile(durs, 0.99)
        m["rounds.duration_mean"] = sum(durs) / len(durs)
        m["rounds.duration_max"] = durs[-1]

    def _mean(field: str, over: List[dict]) -> Optional[float]:
        vals = [
            float(r[field]) for r in over
            if isinstance(r.get(field), (int, float))
        ]
        return sum(vals) / len(vals) if vals else None

    for field, out in (
        ("participants", "rounds.participants_mean"),
        ("reporters", "rounds.reporters_mean"),
        ("bytes_uploaded", "rounds.bytes_uploaded_mean"),
        ("bytes_broadcast", "rounds.bytes_broadcast_mean"),
    ):
        val = _mean(field, completed)
        if val is not None:
            m[out] = val

    n_participants = sum(
        _count(r.get("participants")) for r in completed
    )
    if n_participants:
        m["rounds.straggler_rate"] = sum(
            _count(r.get("stragglers")) for r in completed
        ) / n_participants

    for prefix, snap in (("", snapshot), ("fleet:", fleet_snapshot),
                         ("edge:", edge_snapshot)):
        if not snap:
            continue
        for k, v in (snap.get("counters") or {}).items():
            m[f"{prefix}counter:{k}"] = float(v)
        for k, v in (snap.get("gauges") or {}).items():
            m[f"{prefix}gauge:{k}"] = float(v)
        for name, st in (snap.get("timers") or {}).items():
            for stat, key in _TIMER_STATS.items():
                if key in st:
                    m[f"{prefix}timer:{name}:{stat}"] = float(st[key])
    if loadgen_snapshot:
        for k, v in (loadgen_snapshot.get("counters") or {}).items():
            m[f"loadgen:{k}"] = float(v)
        for k, v in (loadgen_snapshot.get("gauges") or {}).items():
            m[f"loadgen:{k}"] = float(v)
    return m


def derive_history_metrics(history: Optional[List[dict]]) -> Dict[str, float]:
    """``history:*`` metrics from a ``/metrics/history`` snapshot ring.

    ``history:delta:<counter>`` is last-minus-first over the ring;
    ``history:rate:<counter>`` divides that by the ring's wall-clock
    span. With fewer than two timestamped snapshots only
    ``history:samples`` exists — an asserted rate then resolves missing
    and fails, which is the point: "we stopped recording history" must
    not pass a rate SLO vacuously."""
    m: Dict[str, float] = {}
    snaps = sorted(
        (
            s for s in (history or [])
            if isinstance(s, dict)
            and isinstance(s.get("ts"), (int, float))
        ),
        key=lambda s: s["ts"],
    )
    m["history:samples"] = float(len(snaps))
    if len(snaps) < 2:
        return m
    first, last = snaps[0], snaps[-1]
    span = float(last["ts"]) - float(first["ts"])
    m["history:span_s"] = span
    c0 = first.get("counters") or {}
    c1 = last.get("counters") or {}
    for name in set(c0) | set(c1):
        try:
            delta = float(c1.get(name, 0.0)) - float(c0.get(name, 0.0))
        except (TypeError, ValueError):
            continue
        m[f"history:delta:{name}"] = delta
        if span > 0:
            m[f"history:rate:{name}"] = delta / span
    return m


def derive_alert_metrics(events: Optional[List[dict]]) -> Dict[str, float]:
    """``alert:*`` metrics from the ``alerts.jsonl`` event stream.

    Counts lifecycle *transitions* (one ``firing`` episode per fire, no
    matter how long it burned) rather than sampling gauge state — a
    flap that fired twice must read as 2, and an alert still firing at
    run end must still count. Absence-is-zero (see module docstring):
    with no events at all the caller still resolves every ``alert:``
    address to 0.0."""
    m: Dict[str, float] = {}
    for e in events or []:
        if not isinstance(e, dict):
            continue
        ev = e.get("event")
        rule = e.get("rule")
        if ev == "firing" and rule:
            m[f"alert:fired:{rule}"] = m.get(f"alert:fired:{rule}", 0.0) + 1
            m["alert:fired_total"] = m.get("alert:fired_total", 0.0) + 1
            if e.get("severity") == "page":
                m["alert:pages_fired"] = m.get("alert:pages_fired", 0.0) + 1
        elif ev == "resolved" and rule:
            m[f"alert:resolved:{rule}"] = (
                m.get(f"alert:resolved:{rule}", 0.0) + 1
            )
        elif ev == "forensics":
            m["alert:forensics_bundles"] = (
                m.get("alert:forensics_bundles", 0.0) + 1
            )
    return m


def derive_fairness_metrics(fleet_health: Optional[dict]) -> Dict[str, float]:
    """``fairness:*`` participation-share metrics from the manager's
    ``fleet/health`` snapshot (``fleet_health.json``).

    The runbook cohort bias must speed rounds up WITHOUT starving slow
    clients, so the gate needs a number for "how much of the run's
    participation each health class actually got":

    ``fairness:share:<class>``
        Fraction of all reported updates contributed by that class
        (non-inactive classes only — an inactive client isn't being
        starved by selection, it left).
    ``fairness:clients:<class>``
        Non-inactive client count per class.
    ``fairness:share_per_client:<class>``
        Class share normalized by class size — comparable across
        classes of different sizes; under uniform selection every class
        reads ≈ ``1/total_clients``.
    ``fairness:participation_floor``
        ``min over classes`` of ``share_per_client · total_clients`` —
        1.0 is perfectly proportional participation, and the skew
        scenario asserts this stays above a floor while bias is active.

    NOT absence-is-zero: a run with no health snapshot (or no reports)
    resolves these missing, and an asserted floor then fails — "we
    stopped measuring fairness" must not pass vacuously."""
    m: Dict[str, float] = {}
    clients = (fleet_health or {}).get("clients") or {}
    shares: Dict[str, float] = {}
    counts: Dict[str, float] = {}
    total_reported = 0.0
    for info in clients.values():
        if not isinstance(info, dict):
            continue
        status = info.get("status")
        if not isinstance(status, str) or status == "inactive":
            continue
        rep = info.get("reported")
        rep = float(rep) if isinstance(rep, (int, float)) else 0.0
        shares[status] = shares.get(status, 0.0) + rep
        counts[status] = counts.get(status, 0.0) + 1.0
        total_reported += rep
    if not counts or total_reported <= 0:
        return m
    total_clients = sum(counts.values())
    floor = None
    for status in sorted(counts):
        share = shares.get(status, 0.0) / total_reported
        per_client = share / counts[status]
        m[f"fairness:share:{status}"] = share
        m[f"fairness:clients:{status}"] = counts[status]
        m[f"fairness:share_per_client:{status}"] = per_client
        ratio = per_client * total_clients
        floor = ratio if floor is None else min(floor, ratio)
    if floor is not None:
        m["fairness:participation_floor"] = floor
    return m


def derive_runbook_metrics(
    events: Optional[List[dict]],
    records: Optional[List[dict]] = None,
) -> Dict[str, float]:
    """``runbook:*`` metrics from the ``runbooks.jsonl`` lifecycle
    stream (``baton_tpu.obs.runbooks``) plus the per-round
    ``actuations`` records in ``rounds.jsonl``.

    ``runbook:entered:<rule>`` / ``runbook:exited:<rule>`` count one
    rule's activation/hysteresis-exit transitions (entered AND exited
    ≥1 is the reversibility proof); ``runbook:entered_total`` /
    ``runbook:exited_total`` sum across rules;
    ``runbook:actuated_rounds:<action>`` counts rounds whose record
    carries at least one applied actuation of that action, and
    ``runbook:actuations_total`` counts every applied actuation.
    Absence-is-zero like counters — "the run never remediated" is a
    real, assertable zero."""
    m: Dict[str, float] = {}
    for e in events or []:
        if not isinstance(e, dict):
            continue
        ev = e.get("event")
        rule = e.get("rule")
        if ev == "entered" and rule:
            m[f"runbook:entered:{rule}"] = (
                m.get(f"runbook:entered:{rule}", 0.0) + 1
            )
            m["runbook:entered_total"] = m.get("runbook:entered_total", 0.0) + 1
        elif ev == "exited" and rule:
            m[f"runbook:exited:{rule}"] = (
                m.get(f"runbook:exited:{rule}", 0.0) + 1
            )
            m["runbook:exited_total"] = m.get("runbook:exited_total", 0.0) + 1
    for r in records or []:
        acts = r.get("actuations")
        if not isinstance(acts, list):
            continue
        seen_actions = set()
        for a in acts:
            if not isinstance(a, dict) or not a.get("action"):
                continue
            m["runbook:actuations_total"] = (
                m.get("runbook:actuations_total", 0.0) + 1
            )
            seen_actions.add(a["action"])
        for action in seen_actions:
            m[f"runbook:actuated_rounds:{action}"] = (
                m.get(f"runbook:actuated_rounds:{action}", 0.0) + 1
            )
    return m


def _compare(observed: float, op: str, value: float) -> bool:
    if op == "<=":
        return observed <= value
    if op == ">=":
        return observed >= value
    if op == "<":
        return observed < value
    if op == ">":
        return observed > value
    if op == "==":
        return observed == value
    raise ScenarioError(f"unknown SLO op {op!r} (known: {SLO_OPS})")


def check_assertions(
    assertions: Iterable[SLOAssertion], metrics: Dict[str, float]
) -> List[dict]:
    out = []
    for a in assertions:
        observed = resolve_metric(metrics, a.metric)
        if observed is None:
            status = "missing"
        else:
            status = "pass" if _compare(observed, a.op, a.value) else "fail"
        out.append({
            "metric": a.metric, "op": a.op, "value": a.value,
            "observed": observed, "status": status,
        })
    return out


def load_baseline(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        try:
            data = json.load(fh)
        except ValueError as exc:
            raise ScenarioError(f"{path}: not valid JSON: {exc}") from exc
    metrics = data.get("metrics")
    if not isinstance(metrics, dict) or not metrics:
        raise ScenarioError(f"{path}: baseline needs a non-empty `metrics` map")
    for name, spec in metrics.items():
        if not isinstance(spec, dict) or "value" not in spec:
            raise ScenarioError(f"{path}: baseline metric {name!r} needs `value`")
        if spec.get("direction", "higher_is_better") not in _DIRECTIONS:
            raise ScenarioError(
                f"{path}: baseline metric {name!r} direction must be one of "
                f"{_DIRECTIONS}"
            )
    return data


def check_baseline(
    baseline: dict, metrics: Dict[str, float]
) -> List[dict]:
    """Per-baseline-metric delta report. An entry regresses when the
    observation is worse than ``value`` by more than the tolerance in
    the bad direction — or when the run stopped producing the metric at
    all (the silent-drop failure mode)."""
    results = []
    for name, spec in baseline.get("metrics", {}).items():
        value = float(spec["value"])
        direction = spec.get("direction", "higher_is_better")
        tol = float(spec.get("tolerance", 0.0))
        tol_abs = float(spec.get("tolerance_abs", 0.0))
        observed = resolve_metric(metrics, name)
        entry: Dict[str, Any] = {
            "metric": name, "baseline": value, "direction": direction,
            "observed": observed, "delta": None, "delta_rel": None,
        }
        if observed is None:
            entry["regression"] = True
            entry["note"] = "metric missing from this run"
            results.append(entry)
            continue
        delta = observed - value
        entry["delta"] = delta
        if value:
            entry["delta_rel"] = delta / abs(value)
        slack = abs(value) * tol + tol_abs
        if direction == "higher_is_better":
            entry["regression"] = observed < value - slack
        else:
            entry["regression"] = observed > value + slack
        results.append(entry)
    return results


def derive_compute_metrics(
    records: List[dict],
) -> "tuple[Dict[str, float], Dict[str, str]]":
    """``compute:*`` metrics from completed rounds' ``compute``
    sections. Returns ``(metrics, skips)`` with the null-with-reason
    carve-out (module docstring): a value unmeasured in every round but
    excused in each lands in ``skips``; one that simply vanished stays
    absent and the baseline gate regresses it."""
    metrics: Dict[str, float] = {}
    skips: Dict[str, str] = {}
    sections = [
        r["compute"] for r in records
        if r.get("outcome") == "completed" and isinstance(r.get("compute"), dict)
    ]
    if not sections:
        return metrics, skips
    with_compute = [s for s in sections if s.get("reporters")]
    metrics["compute:rounds_with_compute"] = float(len(with_compute))
    metrics["compute:reporters_mean"] = sum(
        float(s.get("reporters") or 0) for s in sections
    ) / len(sections)

    def fold(key: str, out: str, agg) -> None:
        vals = [
            float(s[key]) for s in sections
            if isinstance(s.get(key), (int, float))
            and not isinstance(s.get(key), bool)
        ]
        if vals:
            metrics[out] = agg(vals)
            return
        for s in sections:
            why = s.get(f"{key}_reason") or s.get(f"{key}_source")
            if isinstance(why, str) and why:
                skips[out] = why
                return

    fold("compile_s", "compute:compile_s_max", max)
    fold("compile_s", "compute:compile_s_mean",
         lambda v: sum(v) / len(v))
    fold("steps", "compute:steps_total", sum)
    fold("samples_per_sec_per_chip",
         "compute:samples_per_sec_per_chip_mean",
         lambda v: sum(v) / len(v))
    fold("mfu", "compute:mfu_mean", lambda v: sum(v) / len(v))
    fold("peak_hbm_gb", "compute:peak_hbm_gb_max", max)
    metrics["compute:recompile_storm_rounds"] = float(sum(
        1 for s in sections if s.get("recompile_storms")
    ))
    return metrics, skips


def derive_bench_metrics(parsed: dict) -> "tuple[Dict[str, float], Dict[str, str]]":
    """Flatten one ``bench.py`` output record into the flat SLO
    namespace under a ``bench:`` prefix, so :func:`check_baseline` can
    gate flagship performance the same way it gates scenario telemetry.

    Returns ``(metrics, skips)``. A numeric field becomes
    ``bench:<name>``; each ``flagship_mfu_recorded`` record becomes
    ``bench:flagship:<model>:mfu`` / ``:rounds_per_sec``. A null
    ``fused_rounds_per_sec`` / ``mfu`` with a recorded excuse
    (``fused_skip_reason`` or ``degraded_reason``) lands in ``skips``
    instead — visible and auditable; a null with NO recorded reason is
    simply absent, which the baseline gate treats as a regression (the
    BENCH_r03→r04 silent-drop class)."""
    metrics: Dict[str, float] = {}
    skips: Dict[str, str] = {}
    for field in ("value", "rounds_per_sec", "dispatch_rounds_per_sec",
                  "fused_rounds_per_sec", "mfu",
                  "samples_per_sec_per_chip", "compile_s"):
        v = parsed.get(field)
        name = f"bench:{'rounds_per_sec' if field == 'value' else field}"
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            metrics[name] = float(v)
        elif v is None and field in parsed:
            reason = parsed.get("fused_skip_reason") or parsed.get(
                "degraded_reason"
            )
            if reason:
                skips[name] = str(reason)
    # donation on/off HBM-plan comparison and the wave1024 recorded
    # number: null with a recorded ``*_reason`` skips; null without one
    # regresses. Records from before bench.py emitted these fields are
    # recognizable by the missing ``donation_enabled`` marker and skip
    # with an explicit pre-schema note instead of failing the gate on
    # history the new code never measured.
    pre_schema = "donation_enabled" not in parsed
    donation = parsed.get("donation_hbm")
    if isinstance(donation, dict):
        delta = donation.get("delta_gb")
        if isinstance(delta, (int, float)) and not isinstance(delta, bool):
            metrics["bench:donation_hbm_delta_gb"] = float(delta)
        for variant in ("donate_on", "donate_off"):
            plan = (donation.get(variant) or {}).get("plan_gb")
            if isinstance(plan, (int, float)) and not isinstance(plan, bool):
                metrics[f"bench:donation_{variant}_plan_gb"] = float(plan)
    elif parsed.get("donation_hbm_reason"):
        skips["bench:donation_hbm_delta_gb"] = str(
            parsed["donation_hbm_reason"])
    elif pre_schema:
        skips["bench:donation_hbm_delta_gb"] = (
            "record predates the donation-plan bench stage")
    wave1024 = parsed.get("wave1024_recorded")
    if isinstance(wave1024, dict):
        rps = wave1024.get("rounds_per_sec")
        if isinstance(rps, (int, float)) and not isinstance(rps, bool):
            metrics["bench:wave1024_rounds_per_sec"] = float(rps)
    elif parsed.get("wave1024_reason"):
        skips["bench:wave1024_rounds_per_sec"] = str(
            parsed["wave1024_reason"])
    elif pre_schema:
        skips["bench:wave1024_rounds_per_sec"] = (
            "record predates the wave1024_reason bench field")
    flagship = parsed.get("flagship_mfu_recorded") or {}
    for rec in flagship.get("records") or []:
        model = rec.get("model")
        if not model:
            continue
        for field in ("mfu", "rounds_per_sec", "tokens_per_sec_per_chip",
                      "peak_hbm_gb"):
            v = rec.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                metrics[f"bench:flagship:{model}:{field}"] = float(v)
    return metrics, skips


def check_bench_baseline(
    baseline: dict, parsed: dict
) -> "tuple[List[dict], Dict[str, str]]":
    """Baseline-delta gate over one bench record. Same comparison rules
    as :func:`check_baseline`, with one bench-specific carve-out: a
    metric that is missing *with a recorded skip reason* reports
    ``skipped`` instead of regressing — an unmeasured flagship number
    must name why (accelerator probe failed, budget exhausted), or it
    fails CI."""
    metrics, skips = derive_bench_metrics(parsed)
    results = check_baseline(baseline, metrics)
    for entry in results:
        reason = skips.get(entry["metric"])
        if entry["regression"] and entry["observed"] is None and reason:
            entry["regression"] = False
            entry["note"] = f"skipped: {reason}"
    return results, skips


def evaluate_slo(
    slo: SLOSpec,
    records: List[dict],
    snapshot: Optional[dict] = None,
    *,
    loadgen_snapshot: Optional[dict] = None,
    fleet_snapshot: Optional[dict] = None,
    edge_snapshot: Optional[dict] = None,
    history: Optional[List[dict]] = None,
    alert_events: Optional[List[dict]] = None,
    fleet_health: Optional[dict] = None,
    runbook_events: Optional[List[dict]] = None,
    baseline: Optional[dict] = None,
    n_torn: int = 0,
    exclude_rounds: Iterable[str] = (),
    scenario_name: Optional[str] = None,
) -> dict:
    """The full SLO verdict for one run.

    ``exclude_rounds`` filters warm-up rounds out of the derived
    ``rounds.*`` metrics by round name (XLA compile time is a property
    of the harness, not the serving path). ``baseline`` overrides the
    on-disk file; otherwise ``slo.baseline`` is loaded when set.
    """
    excluded = set(exclude_rounds)
    kept = [r for r in records if r.get("round") not in excluded]
    metrics = derive_metrics(kept, snapshot, loadgen_snapshot,
                             fleet_snapshot, edge_snapshot)
    if history is not None:
        metrics.update(derive_history_metrics(history))
    if alert_events is not None:
        metrics.update(derive_alert_metrics(alert_events))
    if fleet_health is not None:
        metrics.update(derive_fairness_metrics(fleet_health))
    if runbook_events is not None:
        metrics.update(derive_runbook_metrics(runbook_events, kept))
    compute_metrics, compute_skips = derive_compute_metrics(kept)
    metrics.update(compute_metrics)
    assertions = check_assertions(slo.assertions, metrics)

    baseline_block = None
    if baseline is None and slo.baseline is not None:
        baseline = load_baseline(slo.baseline)
    if baseline is not None:
        results = check_baseline(baseline, metrics)
        for entry in results:
            reason = compute_skips.get(entry["metric"])
            if entry["regression"] and entry["observed"] is None and reason:
                # same carve-out as check_bench_baseline: unmeasured
                # WITH a recorded reason is a visible skip, not a
                # silent regression
                entry["regression"] = False
                entry["note"] = f"skipped: {reason}"
        baseline_block = {
            "path": slo.baseline,
            "results": results,
            "regressions": sum(1 for r in results if r["regression"]),
        }

    ok = all(a["status"] == "pass" for a in assertions) and (
        baseline_block is None or baseline_block["regressions"] == 0
    )
    return {
        "scenario": scenario_name,
        "pass": ok,
        "rounds_evaluated": len(kept),
        "rounds_excluded_warmup": len(records) - len(kept),
        "torn_lines": n_torn,
        "assertions": assertions,
        "baseline": baseline_block,
        "compute_skips": compute_skips,
        "metrics": metrics,
    }


def write_report(report: dict, path: str) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=2, sort_keys=False)
        fh.write("\n")
