"""Open-loop production-traffic scenario harness.

"Millions of users" is a traffic *shape* — diurnal availability, client
churn, stragglers, heterogeneous device speeds, flaky networks — not
just a client count. This package drives a real manager + N in-process
workers over the actual HTTP protocol with that shape, then turns the
telemetry PR 6 records (``rounds.jsonl``, ``/metrics`` histograms) into
a machine-checkable verdict:

- :mod:`baton_tpu.loadgen.scenario` — declarative scenario configs
  (``benchmarks/scenarios/*.json``): phases with availability curves,
  churn rates, faults, device-speed multipliers, and SLO assertions.
- :mod:`baton_tpu.loadgen.engine` — the open-loop driver: rounds are
  started on a fixed clock regardless of whether the previous one
  finished (423 refusals are themselves a measured signal), while a
  ticker modulates worker availability and churns the fleet.
- :mod:`baton_tpu.loadgen.slo` — the evaluator/CI gate: parses
  ``rounds.jsonl`` + the manager metrics snapshot, checks the
  scenario's SLO assertions and deltas vs a committed baseline, and
  writes ``slo_report.json``.

Run:  ``python -m baton_tpu.loadgen benchmarks/scenarios/<name>.json``
"""

from baton_tpu.loadgen.scenario import Scenario, ScenarioError, load_scenario
from baton_tpu.loadgen.slo import evaluate_slo

__all__ = ["Scenario", "ScenarioError", "load_scenario", "evaluate_slo"]
