"""CLI: run one scenario and gate on its SLOs.

    python -m baton_tpu.loadgen benchmarks/scenarios/diurnal_churn.json

Runs the scenario end to end (real manager + workers on loopback),
evaluates the scenario's ``slo`` block over the recorded telemetry, and
writes ``slo_report.json`` next to the other artifacts. Exit code 0
when every assertion passes and nothing regressed vs the committed
baseline; 1 on an SLO failure or baseline regression; 2 on a config
error — so CI can use this directly as a regression gate.

The harness measures the serving path, not the accelerator: training is
tiny linear models, so JAX is pinned to CPU by default
(``--platform keep`` preserves the environment's choice).
"""

import argparse
import asyncio
import json
import logging
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m baton_tpu.loadgen",
        description="open-loop traffic scenario runner + SLO gate",
    )
    ap.add_argument("scenario", help="path to a benchmarks/scenarios/*.json")
    ap.add_argument("--artifacts", default=None,
                    help="artifact dir (default: artifacts/loadgen_<name>)")
    ap.add_argument("--platform", default="cpu",
                    help="JAX_PLATFORMS for the run; 'keep' leaves the "
                         "environment alone (default: cpu)")
    ap.add_argument("--tick", type=float, default=0.1,
                    help="driver tick interval in seconds")
    args = ap.parse_args(argv)

    if args.platform != "keep":
        os.environ["JAX_PLATFORMS"] = args.platform

    # import after the platform pin: these pull in jax
    from baton_tpu.loadgen.engine import run_scenario
    from baton_tpu.loadgen.scenario import ScenarioError, load_scenario
    from baton_tpu.loadgen.slo import evaluate_slo, write_report
    from baton_tpu.obs.alerts import read_alerts_jsonl
    from baton_tpu.obs.runbooks import read_runbooks_jsonl
    from baton_tpu.utils.slog import read_rounds_jsonl, setup_json_logging

    setup_json_logging(level=logging.INFO)
    try:
        scenario = load_scenario(args.scenario)
    except (OSError, ScenarioError) as exc:
        print(f"scenario error: {exc}", file=sys.stderr)
        return 2

    artifacts = args.artifacts or os.path.join(
        "artifacts", f"loadgen_{scenario.name}"
    )
    summary = asyncio.run(run_scenario(scenario, artifacts, tick_s=args.tick))

    rounds_path = os.path.join(artifacts, "rounds.jsonl")
    records, n_torn = read_rounds_jsonl(rounds_path)
    with open(os.path.join(artifacts, "manager_metrics.json"),
              encoding="utf-8") as fh:
        snapshot = json.load(fh)
    with open(os.path.join(artifacts, "loadgen_metrics.json"),
              encoding="utf-8") as fh:
        loadgen_snapshot = json.load(fh)
    with open(os.path.join(artifacts, "worker_metrics.json"),
              encoding="utf-8") as fh:
        fleet_snapshot = json.load(fh)
    edge_snapshot = None
    edge_metrics_path = os.path.join(artifacts, "edge_metrics.json")
    if os.path.exists(edge_metrics_path):
        with open(edge_metrics_path, encoding="utf-8") as fh:
            edge_snapshot = json.load(fh)
    history = None
    history_path = os.path.join(artifacts, "metrics_history.json")
    if os.path.exists(history_path):
        with open(history_path, encoding="utf-8") as fh:
            history = json.load(fh).get("history")
    # the alert lifecycle stream backs the ``alert:*`` SLO namespace;
    # alerting disabled → no file → [] (alert: addresses resolve to 0)
    alerts_path = os.path.join(artifacts, "alerts.jsonl")
    alert_events = (read_alerts_jsonl(alerts_path)[0]
                    if os.path.exists(alerts_path) else [])
    # the actuation lifecycle stream backs ``runbook:*`` the same way;
    # runbooks disabled → no file → [] (runbook: addresses resolve to 0)
    runbooks_path = os.path.join(artifacts, "runbooks.jsonl")
    runbook_events = (read_runbooks_jsonl(runbooks_path)[0]
                      if os.path.exists(runbooks_path) else [])
    # per-class participation shares (``fairness:*``) come from the
    # fleet ledger's final health snapshot; deliberately NOT
    # absence-is-zero — see slo.derive_fairness_metrics
    fleet_health = None
    fleet_health_path = os.path.join(artifacts, "fleet_health.json")
    if os.path.exists(fleet_health_path):
        with open(fleet_health_path, encoding="utf-8") as fh:
            fleet_health = json.load(fh)
    try:
        report = evaluate_slo(
            scenario.slo, records, snapshot,
            loadgen_snapshot=loadgen_snapshot,
            fleet_snapshot=fleet_snapshot,
            edge_snapshot=edge_snapshot,
            history=history,
            alert_events=alert_events,
            fleet_health=fleet_health,
            runbook_events=runbook_events,
            n_torn=n_torn,
            exclude_rounds=summary["warmup_round_names"],
            scenario_name=scenario.name,
        )
    except (OSError, ScenarioError) as exc:
        print(f"baseline error: {exc}", file=sys.stderr)
        return 2
    report_path = os.path.join(artifacts, "slo_report.json")
    write_report(report, report_path)

    n_fail = sum(1 for a in report["assertions"] if a["status"] != "pass")
    n_reg = (report["baseline"] or {}).get("regressions", 0)
    verdict = "PASS" if report["pass"] else "FAIL"
    print(
        f"[{verdict}] scenario={scenario.name} "
        f"rounds={report['rounds_evaluated']} "
        f"(+{report['rounds_excluded_warmup']} warmup) "
        f"assertions={len(report['assertions']) - n_fail}"
        f"/{len(report['assertions'])} pass "
        f"baseline_regressions={n_reg} torn_lines={report['torn_lines']} "
        f"report={report_path}"
    )
    for a in report["assertions"]:
        if a["status"] != "pass":
            print(f"  assertion {a['status']}: {a['metric']} {a['op']} "
                  f"{a['value']} (observed: {a['observed']})")
    if report["baseline"]:
        for r in report["baseline"]["results"]:
            if r["regression"]:
                print(f"  regression: {r['metric']} baseline={r['baseline']} "
                      f"observed={r['observed']} "
                      f"({r.get('note') or 'beyond tolerance'})")
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
