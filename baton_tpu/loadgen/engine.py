"""Open-loop scenario driver: a real federation under synthetic traffic.

The engine spins up one real manager and ``workers.count`` real workers
— actual :class:`~baton_tpu.server.http_manager.Experiment` /
:class:`~baton_tpu.server.http_worker.ExperimentWorker` instances on
loopback sockets, nothing mocked — then plays the scenario's phases
against them:

- **Open-loop rounds.** ``GET start_round`` fires every
  ``rounds.interval_s`` seconds of scenario time whether or not the
  previous round finished. A busy manager answers 423 and the refusal
  is *counted*, not retried — arrival rate is the independent variable,
  exactly like production traffic, so overload shows up as a refusal
  rate instead of being silently absorbed by a closed feedback loop.
- **Availability.** Each tick computes the phase's curve level ``a`` and
  marks the first ``round(a × alive)`` workers (by index) available.
  Unavailable workers answer ``round_start`` with an injected 503 — the
  same refusal a phone off-charger would send — which the manager
  counts (``broadcast_rejected_503``) and excludes from the round
  without evicting the client. Deterministic rank-based selection keeps
  runs reproducible.
- **Churn.** Leave/join rates accumulate per tick; a leave tears the
  worker's HTTP server down cold (no deregister — the manager learns
  via notify failures and the TTL cull), a join spawns a brand-new
  worker mid-run. The fleet the SLOs see is never the fleet that
  registered.
- **Stragglers / device speeds.** ``workers.speeds`` maps fleet
  fractions to ``train_time_scale`` multipliers; the manager's
  ``round_timeout`` watchdog turns slow tails into recorded
  ``stragglers`` in ``rounds.jsonl``.
- **Faults.** Phase-scoped :class:`~baton_tpu.utils.faults.FaultInjector`
  rules on the manager and/or every worker (delays, errors, connection
  drops), removed when the phase ends.

Warm-up rounds (XLA compile) run before the scenario clock starts with
everything available and no faults; their round names are recorded so
the SLO evaluator excludes them. Artifacts land in the run directory:
``rounds.jsonl`` (written by the manager), ``manager_metrics.json``
(the ``/metrics`` scrape), ``loadgen_metrics.json`` (driver counters),
``scenario_summary.json`` (phase timeline + per-round annotations),
plus — when the scenario's ``alerts`` block is enabled (the default) —
``alerts.jsonl`` (the manager's alert lifecycle stream, backing the SLO
evaluator's ``alert:*`` namespace), ``alerts_status.json`` (the final
``GET /alerts`` snapshot), ``forensics_index.json`` and a
``forensics/`` directory of content-addressed bundles. When the
scenario's ``runbooks`` block is enabled (opt-in), the manager also
writes ``runbooks.jsonl`` (the actuation lifecycle stream, backing the
SLO evaluator's ``runbook:*`` namespace) and the driver scrapes the
final ``GET /runbooks`` snapshot into ``runbooks_status.json``.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import os
import random
import shutil
import socket
import time
from typing import List, Optional

import numpy as np
import aiohttp
from aiohttp import web

from baton_tpu.core.training import make_local_trainer
from baton_tpu.data.synthetic import linear_client_data
from baton_tpu.loadgen.scenario import PhaseSpec, Scenario
from baton_tpu.models.linear import linear_regression_model
from baton_tpu.server.edge import EdgeAggregator
from baton_tpu.server.http_manager import Manager
from baton_tpu.server.http_worker import ExperimentWorker
from baton_tpu.server.topology import EdgeTopology
from baton_tpu.utils.faults import FaultInjector, Rule
from baton_tpu.utils.metrics import Metrics
from baton_tpu.utils.slog import read_rounds_jsonl

log = logging.getLogger("baton_tpu.loadgen")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _TeeMetrics(Metrics):
    """Per-worker registry that mirrors every write into the shared
    fleet registry. The evaluator's ``fleet:*`` namespace keeps its
    aggregate semantics (one histogram across the whole simulated
    fleet) while each worker's own copy makes per-worker attribution —
    *which* device's heartbeat went bad — possible after the run."""

    def __init__(self, shared: Metrics) -> None:
        super().__init__()
        self._shared = shared

    def inc(self, name: str, value: float = 1.0) -> None:
        super().inc(name, value)
        self._shared.inc(name, value)

    def set_gauge(self, name: str, value: float) -> None:
        super().set_gauge(name, value)
        self._shared.set_gauge(name, value)

    def observe(self, name, seconds, exemplar=None) -> None:
        super().observe(name, seconds, exemplar=exemplar)
        self._shared.observe(name, seconds, exemplar=exemplar)


class _WorkerSlot:
    """One simulated device: its worker, server runner, fault injector
    (availability gate + phase faults), and the flags the ticker flips."""

    __slots__ = ("idx", "worker", "runner", "injector", "available", "alive")

    def __init__(self, idx: int, worker: ExperimentWorker,
                 runner: web.AppRunner, injector: FaultInjector) -> None:
        self.idx = idx
        self.worker = worker
        self.runner = runner
        self.injector = injector
        self.available = True
        self.alive = True


class _EdgeSlot:
    """One edge aggregator: its server runner, loopback port, and
    liveness (a killed edge's runner is torn down cold — no drain, no
    goodbye, exactly like a zone loss)."""

    __slots__ = ("name", "edge", "runner", "port", "alive")

    def __init__(self, name: str, edge: EdgeAggregator,
                 runner: web.AppRunner, port: int) -> None:
        self.name = name
        self.edge = edge
        self.runner = runner
        self.port = port
        self.alive = True


class _RootSlot:
    """One root replica (``manager.standby_roots``): its Experiment, its
    server runner, its loopback port. The active's kill is the same cold
    teardown as an edge death; a standby becomes the new active via the
    lease-expiry promotion in server/replication."""

    __slots__ = ("rid", "exp", "runner", "port", "alive")

    def __init__(self, rid: str, exp, runner: web.AppRunner,
                 port: int) -> None:
        self.rid = rid
        self.exp = exp
        self.runner = runner
        self.port = port
        self.alive = True


class ScenarioRunner:
    """Drives one scenario end to end; :meth:`run` returns the summary
    dict (also written to ``scenario_summary.json``)."""

    def __init__(self, scenario: Scenario, artifacts_dir: str,
                 tick_s: float = 0.1) -> None:
        self.scenario = scenario
        self.artifacts_dir = artifacts_dir
        self.tick_s = tick_s
        self.metrics = Metrics()
        # one shared registry for every simulated worker: fleet-wide
        # heartbeat/upload histograms instead of per-process islands
        # (exported as worker_metrics.json, addressed as ``fleet:*``)
        self.fleet_metrics = Metrics()
        # likewise one shared registry across the edge tier (exported
        # as edge_metrics.json, addressed as ``edge:*``)
        self.edge_metrics = Metrics()
        self._edge_slots: List[_EdgeSlot] = []
        self._root_slots: List[_RootSlot] = []
        self._topology: Optional[EdgeTopology] = None
        self.rounds_path = os.path.join(artifacts_dir, "rounds.jsonl")
        self.alerts_path = os.path.join(artifacts_dir, "alerts.jsonl")
        self.runbooks_path = os.path.join(artifacts_dir, "runbooks.jsonl")
        self._rng = random.Random(scenario.seed)
        self._nprng = np.random.default_rng(scenario.seed)
        self._slots: List[_WorkerSlot] = []
        self._next_idx = 0
        self._leave_debt = 0.0
        self._join_debt = 0.0
        self._runners: List[web.AppRunner] = []
        self._round_tasks: List[asyncio.Task] = []
        self._phase_rules: List[tuple] = []   # (injector, Rule)
        self._active_worker_faults: List = []  # FaultSpec, for joiners
        self._session: Optional[aiohttp.ClientSession] = None
        self._exp = None
        self._mport = 0
        self._model = None
        self._trainer = None
        self._coef = None
        self.warmup_round_names: List[str] = []
        self.phase_log: List[dict] = []

    # -- edge tier -----------------------------------------------------
    async def _spawn_edge(self, i: int) -> _EdgeSlot:
        scn = self.scenario
        port = _free_port()
        eapp = web.Application()
        edge = EdgeAggregator(
            eapp, f"127.0.0.1:{self._mport}", name=scn.name, port=port,
            edge_name=f"e{i}",
            heartbeat_time=scn.edges.heartbeat_time,
            flush_after_s=scn.edges.flush_after_s,
            metrics=self.edge_metrics,
        )
        runner = web.AppRunner(eapp)
        await runner.setup()
        await web.TCPSite(runner, "127.0.0.1", port).start()
        slot = _EdgeSlot(f"e{i}", edge, runner, port)
        self._edge_slots.append(slot)
        self._runners.append(runner)
        self.metrics.inc("scenario_edges_started")
        return slot

    async def _kill_edge(self, slot: _EdgeSlot) -> None:
        """Cold teardown: the cohort's workers discover the loss via
        transport errors and fall back direct to the root."""
        slot.alive = False
        if self._topology is not None:
            self._topology.mark_dead(slot.name)
        with contextlib.suppress(Exception):
            await slot.runner.cleanup()
        self.metrics.inc("scenario_edges_killed")
        log.info("loadgen: killed edge %s (port %d)", slot.name, slot.port)

    def _edge_for(self, idx: int) -> Optional[str]:
        """``host:port`` of the live edge a worker routes through, via
        the consistent-hash ring — None in the flat topology."""
        if self._topology is None:
            return None
        name = self._topology.assign(f"w{idx}")
        for slot in self._edge_slots:
            if slot.name == name:
                return f"127.0.0.1:{slot.port}"
        return None

    # -- root replicas -------------------------------------------------
    async def _spawn_standby(self, i: int, port: int,
                             standby_ports: List[int]) -> _RootSlot:
        """One warm standby root: a real manager on its own socket whose
        journal file is written by the WalReceiver. It shares the
        active's rounds/alerts log paths — after promotion its records
        continue the same streams the SLO evaluator reads. Its alert
        rules are empty (a standby evaluating fleet rules against an
        empty registry would fire spurious pages); ``log_event`` aborts
        still land in alerts.jsonl."""
        scn = self.scenario
        wal_dir = os.path.join(self.artifacts_dir, "wal")
        rid = f"root-{i}"
        sapp = web.Application()
        exp = Manager(sapp).register_experiment(
            self._model, name=scn.name,
            round_timeout=scn.manager.round_timeout,
            client_ttl=scn.manager.client_ttl,
            cohort_fraction=scn.manager.cohort_fraction,
            min_cohort=scn.manager.min_cohort,
            ingest_workers=scn.manager.ingest_workers,
            streaming_aggregation=scn.manager.streaming_aggregation,
            rounds_log_path=self.rounds_path,
            alert_rules=(), alerts_interval_s=0.0,
            alerts_log_path=(self.alerts_path if scn.alerts.enabled
                             else None),
            journal_path=os.path.join(wal_dir, f"{rid}.jsonl"),
            journal_fsync="never",
            recovery_policy="resume",
            ha_role="standby",
            ha_replica_id=rid,
            ha_standbys=[f"http://127.0.0.1:{p}" for p in standby_ports
                         if p != port],
            ha_lease_s=scn.manager.ha_lease_s,
            ha_ship_interval_s=scn.manager.ha_ship_interval_s,
            ha_promote_grace_s=scn.manager.ha_promote_grace_s,
            ha_token=f"loadgen-{scn.name}",
            chunk_spill_dir=os.path.join(wal_dir, f"spill-{rid}"),
        )
        runner = web.AppRunner(sapp)
        await runner.setup()
        await web.TCPSite(runner, "127.0.0.1", port).start()
        self._runners.append(runner)
        slot = _RootSlot(rid, exp, runner, port)
        self._root_slots.append(slot)
        return slot

    async def _kill_root(self) -> None:
        """Cold teardown of the active root replica, then block until a
        warm standby observes lease expiry and promotes. The open-loop
        clock, drain, and artifact scrapes all follow ``self._mport`` /
        ``self._exp``, so retargeting them here moves the whole driver
        to the new active."""
        scn = self.scenario
        active = next(
            (s for s in self._root_slots
             if s.alive and s.port == self._mport),
            None,
        )
        if active is None:
            log.warning("loadgen: kill_root with no live active root")
            return
        # strike at the most adversarial moment: a round mid-flight with
        # some updates accepted and already WAL-shipped, others still
        # outstanding. That is the moment the chaos target is about —
        # the promoted standby must resume the round and reuse the
        # journaled payloads (zero retraining for delivered clients).
        # The driver orchestrates the victim round itself instead of
        # hoping phase-boundary timing lands inside one: wait for the
        # fleet to go idle, fire a fresh round (the phase's faults —
        # e.g. a manager-side update delay — hold part of the fleet
        # outstanding), wait for the accepted set to stop growing and
        # for the shipper to put it on the standbys, then pull the plug.
        standbys = [s for s in self._root_slots
                    if s.alive and s.port != self._mport]

        def _idle() -> bool:
            if active.exp.rounds.in_progress:
                return False
            return all(
                not s.worker.round_in_progress
                and s.worker._pending is None
                for s in self._slots if s.alive
            )

        if not await self._wait(_idle, timeout_s=20.0):
            log.warning("loadgen: kill_root: fleet never went idle; "
                        "striking anyway")
        await self._fire_round()
        rm = active.exp.rounds

        def _partial() -> bool:
            return (rm.in_progress and bool(rm.client_responses)
                    and rm.clients_left > 0)

        caught = await self._wait(_partial, timeout_s=15.0, dt=0.01)
        if caught:
            # let the accepted set settle (all undelayed updates in, the
            # delayed ones still outstanding), then require the WAL
            # through the last accepted payload applied on every standby
            deadline = asyncio.get_running_loop().time() + 5.0
            n_resp = -1
            while asyncio.get_running_loop().time() < deadline:
                n = len(rm.client_responses)
                if n == n_resp or not _partial():
                    break
                n_resp = n
                await asyncio.sleep(0.2)
            try:
                jsize = os.path.getsize(active.exp.journal.path)
            except (OSError, AttributeError):
                jsize = 0
            await self._wait(
                lambda: all(
                    s.exp._wal_receiver is not None
                    and (s.exp._wal_receiver.status().get("applied_offset")
                         or 0) >= jsize
                    for s in standbys
                ),
                timeout_s=5.0, dt=0.01,
            )
        else:
            log.warning("loadgen: kill_root found no mid-round window "
                        "within 15s; killing the active anyway")
        active.alive = False
        with contextlib.suppress(Exception):
            await active.runner.cleanup()
        self.metrics.inc("scenario_roots_killed")
        log.info("loadgen: killed active root %s (port %d)",
                 active.rid, active.port)
        standbys = [s for s in self._root_slots if s.alive]
        promoted: List[_RootSlot] = []

        def _find():
            promoted[:] = [s for s in standbys if s.exp.ha_role == "active"]
            return bool(promoted)

        timeout = max(
            30.0,
            20 * (scn.manager.ha_lease_s + scn.manager.ha_promote_grace_s),
        )
        if not await self._wait(_find, timeout_s=timeout):
            raise RuntimeError(
                f"no standby promoted within {timeout:.0f}s of killing "
                f"{active.rid}"
            )
        new = promoted[0]
        self._exp = new.exp
        self._mport = new.port
        log.info("loadgen: %s promoted (epoch %d), driver retargeted",
                 new.rid, new.exp.ha_epoch)

    # -- fleet ---------------------------------------------------------
    async def _spawn_worker(self) -> _WorkerSlot:
        scn = self.scenario
        idx = self._next_idx
        self._next_idx += 1
        data = linear_client_data(
            self._nprng,
            coef=self._coef,
            min_batches=scn.workers.min_batches,
            max_batches=scn.workers.max_batches,
            batch_size=scn.workers.batch_size,
        )
        inj = FaultInjector()
        wapp = web.Application(middlewares=[inj.middleware])
        # with root replicas the worker's failover ring holds every
        # other root (joiners after a failover ring back to the dead
        # active too — rotation skips it on transport error)
        failover = [f"127.0.0.1:{s.port}" for s in self._root_slots
                    if s.port != self._mport] or None
        worker = ExperimentWorker(
            wapp, self._model, f"127.0.0.1:{self._mport}",
            name=scn.name, port=_free_port(),
            failover=failover,
            heartbeat_time=scn.workers.heartbeat_time,
            trainer=self._trainer,
            get_data=lambda d=data: (d, d["x"].shape[0]),
            rng_seed=idx,
            outbox_backoff=(0.05, 0.5),
            upload_chunk_bytes=scn.workers.upload_chunk_bytes,
            train_time_scale=scn.workers.speed_for(idx),
            edge=self._edge_for(idx),
            edge_retry_s=scn.edges.retry_s,
        )
        worker.metrics = _TeeMetrics(self.fleet_metrics)
        runner = web.AppRunner(wapp)
        await runner.setup()
        await web.TCPSite(runner, "127.0.0.1", worker.port).start()
        slot = _WorkerSlot(idx, worker, runner, inj)
        # the availability gate: a standing 503 on round_start that only
        # fires while the ticker has the slot marked unavailable — the
        # manager counts the refusal and skips the worker WITHOUT
        # evicting it (a 404 would force re-registration instead)
        inj.error("round_start", status=503,
                  gate=lambda s=slot: not s.available)
        for fs in self._active_worker_faults:
            self._install_fault(fs, inj, record=True)
        self._slots.append(slot)
        self._runners.append(runner)
        return slot

    async def _reap(self, slot: _WorkerSlot) -> None:
        """Cancel a worker's background delivery tasks. A departed
        worker's outbox would otherwise retry into its own closed
        session forever."""
        for task in (slot.worker._outbox_task, slot.worker._ship_task):
            if task is not None and not task.done():
                task.cancel()
                with contextlib.suppress(Exception, asyncio.CancelledError):
                    await task

    async def _leave(self, slot: _WorkerSlot) -> None:
        slot.alive = False
        slot.available = False
        with contextlib.suppress(Exception):
            await slot.runner.cleanup()
        await self._reap(slot)
        self.metrics.inc("scenario_workers_left")

    # -- faults --------------------------------------------------------
    def _install_fault(self, fs, inj: FaultInjector,
                       record: bool = False) -> Rule:
        if fs.action == "error":
            rule = inj.error(fs.match, status=fs.status, times=fs.times)
        elif fs.action == "delay":
            rule = inj.delay(fs.match, seconds=fs.delay_s, times=fs.times)
        else:
            rule = inj.drop(fs.match, times=fs.times)
        if record:
            self._phase_rules.append((inj, rule))
        return rule

    async def _enter_phase(self, idx: int, phase: PhaseSpec,
                           minj: FaultInjector, elapsed: float) -> None:
        for inj, rule in self._phase_rules:
            inj.remove(rule)
        self._phase_rules.clear()
        for k in phase.kill_edges:
            if k < len(self._edge_slots) and self._edge_slots[k].alive:
                await self._kill_edge(self._edge_slots[k])
        self._active_worker_faults = []
        for fs in phase.faults:
            if fs.target == "manager":
                # NOTE: manager faults always target the run's ORIGINAL
                # active root (its injector); faults in phases after a
                # kill_root land on the dead replica and are inert
                self._install_fault(fs, minj, record=True)
            else:
                self._active_worker_faults.append(fs)
                for slot in self._slots:
                    if slot.alive:
                        self._install_fault(fs, slot.injector, record=True)
        if phase.kill_root:
            # after fault installation: the victim round _kill_root
            # fires must run under this phase's faults (that is how a
            # scenario holds part of the fleet outstanding at the kill)
            await self._kill_root()
        self.metrics.set_gauge("scenario_phase_index", idx)
        self.phase_log.append({
            "phase": phase.name, "index": idx,
            "scenario_t": round(elapsed, 3), "wall_ts": None,  # stamped below
        })
        log.info("loadgen: entering phase %r (t=%.1fs, %d faults)",
                 phase.name, elapsed, len(phase.faults))

    # -- ticker pieces -------------------------------------------------
    def _apply_availability(self, level: float) -> None:
        alive = [s for s in self._slots if s.alive]
        alive.sort(key=lambda s: s.idx)
        k = int(round(level * len(alive)))
        for rank, slot in enumerate(alive):
            slot.available = rank < k
        self.metrics.set_gauge("scenario_availability", level)
        self.metrics.set_gauge("scenario_workers_available", k)
        self.metrics.set_gauge("scenario_workers_alive", len(alive))

    async def _apply_churn(self, phase: PhaseSpec, dt: float) -> None:
        self._leave_debt += phase.churn.leave_per_s * dt
        self._join_debt += phase.churn.join_per_s * dt
        while self._leave_debt >= 1.0:
            self._leave_debt -= 1.0
            alive = [s for s in self._slots if s.alive]
            if len(alive) <= 1:   # never churn the fleet to extinction
                break
            await self._leave(self._rng.choice(alive))
        while self._join_debt >= 1.0:
            self._join_debt -= 1.0
            await self._spawn_worker()
            self.metrics.inc("scenario_workers_joined")

    async def _fire_round(self) -> None:
        scn = self.scenario
        url = (f"http://127.0.0.1:{self._mport}/{scn.name}/start_round"
               f"?n_epoch={scn.rounds.n_epoch}")
        try:
            async with self._session.get(url) as resp:
                await resp.read()
                if resp.status == 200:
                    self.metrics.inc("scenario_rounds_started")
                elif resp.status == 423:
                    self.metrics.inc("scenario_rounds_refused_423")
                else:
                    self.metrics.inc("scenario_start_round_errors")
        except (aiohttp.ClientError, asyncio.TimeoutError):
            self.metrics.inc("scenario_start_round_errors")

    async def _wait(self, cond, timeout_s: float, dt: float = 0.05) -> bool:
        deadline = asyncio.get_running_loop().time() + timeout_s
        while asyncio.get_running_loop().time() < deadline:
            if cond():
                return True
            await asyncio.sleep(dt)
        return bool(cond())

    # -- the run -------------------------------------------------------
    async def run(self) -> dict:
        scn = self.scenario
        os.makedirs(self.artifacts_dir, exist_ok=True)
        # a fresh run must not inherit a previous run's rounds or alerts
        with contextlib.suppress(OSError):
            os.remove(self.rounds_path)
        with contextlib.suppress(OSError):
            os.remove(self.alerts_path)

        self._model = linear_regression_model(scn.model_dim)
        # ground-truth coefficients sized to the scenario's model (the
        # synthetic-data default is a fixed 10-dim demo vector)
        self._coef = self._nprng.standard_normal(scn.model_dim).astype(
            np.float32
        )
        self._trainer = make_local_trainer(
            linear_regression_model(scn.model_dim),
            batch_size=scn.workers.batch_size,
            learning_rate=scn.workers.learning_rate,
        )
        self._mport = _free_port()
        standby_ports = [_free_port()
                         for _ in range(scn.manager.standby_roots)]
        minj = FaultInjector()
        mapp = web.Application(middlewares=[minj.middleware])
        if scn.alerts.enabled:
            # rules=None evaluates the manager's default pack; an
            # explicit scenario list replaces it (already validated at
            # scenario load)
            alerts_kwargs = dict(
                alert_rules=(None if scn.alerts.rules is None
                             else [dict(r) for r in scn.alerts.rules]),
                alerts_log_path=self.alerts_path,
                alerts_interval_s=scn.alerts.interval_s,
                alerts_rounds_window=scn.alerts.rounds_window,
                forensics_dir=os.path.join(self.artifacts_dir, "forensics"),
            )
        else:
            alerts_kwargs = dict(alert_rules=(), alerts_interval_s=0.0)
        runbooks_kwargs = {}
        if scn.runbooks.enabled:
            # actuation rides the alert evaluator's tick; rules=None
            # loads the manager's default remediation pack (already
            # validated at scenario load, same contract as alerts)
            runbooks_kwargs = dict(
                runbook_rules=("default" if scn.runbooks.rules is None
                               else [dict(r) for r in scn.runbooks.rules]),
                runbooks_log_path=self.runbooks_path,
            )
            if not scn.alerts.enabled:
                # the runbook engine evaluates on the alerts tick —
                # keep the tick alive even with alerting itself off
                alerts_kwargs["alerts_interval_s"] = scn.alerts.interval_s
        ha_kwargs = {}
        if standby_ports:
            # replicated control plane: the active journals every round
            # (payloads included) and ships the WAL to the warm standbys;
            # workers get the standby list as their failover ring
            wal_dir = os.path.join(self.artifacts_dir, "wal")
            # a fresh run must not recover a previous run's journal
            shutil.rmtree(wal_dir, ignore_errors=True)
            os.makedirs(wal_dir, exist_ok=True)
            ha_kwargs = dict(
                journal_path=os.path.join(wal_dir, "root-0.jsonl"),
                journal_fsync="never",
                recovery_policy="resume",
                ha_role="active",
                ha_replica_id="root-0",
                ha_standbys=[f"http://127.0.0.1:{p}" for p in standby_ports],
                ha_lease_s=scn.manager.ha_lease_s,
                ha_ship_interval_s=scn.manager.ha_ship_interval_s,
                ha_promote_grace_s=scn.manager.ha_promote_grace_s,
                ha_token=f"loadgen-{scn.name}",
                chunk_spill_dir=os.path.join(wal_dir, "spill-root-0"),
            )
        self._exp = Manager(mapp).register_experiment(
            self._model, name=scn.name,
            round_timeout=scn.manager.round_timeout,
            client_ttl=scn.manager.client_ttl,
            cohort_fraction=scn.manager.cohort_fraction,
            min_cohort=scn.manager.min_cohort,
            ingest_workers=scn.manager.ingest_workers,
            streaming_aggregation=scn.manager.streaming_aggregation,
            rounds_log_path=self.rounds_path,
            **alerts_kwargs,
            **runbooks_kwargs,
            **ha_kwargs,
        )
        mrunner = web.AppRunner(mapp)
        await mrunner.setup()
        await web.TCPSite(mrunner, "127.0.0.1", self._mport).start()
        self._runners.append(mrunner)
        self._root_slots.append(
            _RootSlot("root-0", self._exp, mrunner, self._mport)
        )
        for i, port in enumerate(standby_ports, start=1):
            await self._spawn_standby(i, port, standby_ports)
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=60)
        )
        try:
            return await self._run_inner(minj)
        finally:
            await self._teardown()

    async def _run_inner(self, minj: FaultInjector) -> dict:
        scn = self.scenario
        exp = self._exp

        if scn.edges.count > 0:
            self._topology = EdgeTopology(
                [f"e{i}" for i in range(scn.edges.count)]
            )
            for i in range(scn.edges.count):
                await self._spawn_edge(i)
        for _ in range(scn.workers.count):
            await self._spawn_worker()
        # each edge registers its own root credentials too
        expected = scn.workers.count + scn.edges.count
        ok = await self._wait(
            lambda: len(exp.registry) >= expected, timeout_s=30.0
        )
        if not ok:
            raise RuntimeError(
                f"fleet failed to register: {len(exp.registry)}"
                f"/{expected} after 30s"
            )

        # warm-up: compile + first blob fetch outside the scenario clock
        for _ in range(scn.rounds.warmup):
            before = exp.rounds.n_rounds
            await self._fire_round()
            await self._wait(
                lambda: exp.rounds.n_rounds > before
                or not exp.rounds.in_progress,
                timeout_s=max(60.0, 2 * scn.manager.round_timeout),
            )
            self.metrics.inc("scenario_warmup_rounds")
        # whatever landed in rounds.jsonl so far is warm-up; the SLO
        # evaluator excludes these names (compile time is a harness
        # property, not a serving-path one)
        self.warmup_round_names = [
            r.get("round") for r in read_rounds_jsonl(self.rounds_path)[0]
        ]

        loop = asyncio.get_running_loop()
        t0 = loop.time()
        wall0 = time.time()
        last_tick = t0
        next_round_at = t0 + 0.01
        rounds_fired = 0
        cur_phase = -1
        total_s = scn.total_s
        while True:
            now = loop.time()
            elapsed = now - t0
            if elapsed >= total_s:
                break
            dt = now - last_tick
            last_tick = now
            pidx, phase, t_in = scn.phase_at(elapsed)
            if pidx != cur_phase:
                cur_phase = pidx
                await self._enter_phase(pidx, phase, minj, elapsed)
                self.phase_log[-1]["wall_ts"] = round(time.time(), 6)
            self._apply_availability(phase.availability.level_at(t_in))
            await self._apply_churn(phase, dt)
            if now >= next_round_at and (
                scn.rounds.max_rounds is None
                or rounds_fired < scn.rounds.max_rounds
            ):
                rounds_fired += 1
                next_round_at += scn.rounds.interval_s
                self._round_tasks.append(
                    asyncio.ensure_future(self._fire_round())
                )
            await asyncio.sleep(self.tick_s)

        # drain: everyone back online, no new rounds; let the last round
        # finish (the round_timeout watchdog force-finishes stragglers)
        self._apply_availability(1.0)
        for inj, rule in self._phase_rules:
            inj.remove(rule)
        self._phase_rules.clear()
        if self._round_tasks:
            await asyncio.wait(self._round_tasks, timeout=60.0)
        grace = (scn.rounds.drain_grace_s
                 if scn.rounds.drain_grace_s is not None
                 else scn.manager.round_timeout + 5.0)
        # drain against the *current* active — a kill_root phase may
        # have retargeted self._exp mid-run
        settled = await self._wait(
            lambda: not self._exp.rounds.in_progress, timeout_s=grace
        )
        if not settled:
            self.metrics.inc("scenario_rounds_forced_end")
            self._exp.end_round()

        # artifacts ---------------------------------------------------
        async with self._session.get(
            f"http://127.0.0.1:{self._mport}/{scn.name}/metrics"
        ) as resp:
            manager_metrics = await resp.json()
        # the manager's timestamped snapshot ring: the SLO evaluator's
        # ``history:*`` rate/delta namespace derives from this
        metrics_history = None
        try:
            async with self._session.get(
                f"http://127.0.0.1:{self._mport}/{scn.name}"
                "/metrics/history"
            ) as resp:
                if resp.status == 200:
                    metrics_history = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError):
            pass
        fleet_health = None
        try:
            async with self._session.get(
                f"http://127.0.0.1:{self._mport}/{scn.name}/fleet/health"
            ) as resp:
                if resp.status == 200:
                    fleet_health = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError):
            pass
        runbooks_status = None
        if scn.runbooks.enabled:
            try:
                async with self._session.get(
                    f"http://127.0.0.1:{self._mport}/{scn.name}/runbooks"
                ) as resp:
                    if resp.status == 200:
                        runbooks_status = await resp.json()
            except (aiohttp.ClientError, asyncio.TimeoutError):
                pass
        alerts_status = None
        forensics_index = None
        if scn.alerts.enabled:
            try:
                async with self._session.get(
                    f"http://127.0.0.1:{self._mport}/{scn.name}/alerts"
                ) as resp:
                    if resp.status == 200:
                        alerts_status = await resp.json()
                async with self._session.get(
                    f"http://127.0.0.1:{self._mport}/{scn.name}/forensics"
                ) as resp:
                    if resp.status == 200:
                        forensics_index = await resp.json()
            except (aiohttp.ClientError, asyncio.TimeoutError):
                pass
        loadgen_metrics = self.metrics.snapshot()
        worker_metrics = self.fleet_metrics.snapshot()
        edge_metrics = self.edge_metrics.snapshot()
        records, n_torn = read_rounds_jsonl(self.rounds_path)
        summary = {
            "scenario": scn.name,
            "total_s": total_s,
            "edges": {
                "count": scn.edges.count,
                "alive": sum(1 for s in self._edge_slots if s.alive),
            },
            "roots": {
                "count": 1 + scn.manager.standby_roots,
                "alive": sum(1 for s in self._root_slots if s.alive),
                "active": next(
                    (s.rid for s in self._root_slots
                     if s.port == self._mport), None,
                ),
            },
            "wall_started": round(wall0, 6),
            "rounds_fired": rounds_fired,
            "warmup_round_names": self.warmup_round_names,
            "phases": self.phase_log,
            "torn_lines": n_torn,
            "rounds": self._annotate_rounds(records, wall0),
            "counters": loadgen_metrics["counters"],
        }
        self._write_json("manager_metrics.json", manager_metrics)
        self._write_json("worker_metrics.json", worker_metrics)
        # per-worker attribution rides in a sibling artifact so the
        # aggregate fleet:* addresses keep their exact semantics
        self._write_json("worker_metrics_per_worker.json", {
            f"w{slot.idx}": slot.worker.metrics.snapshot()
            for slot in self._slots
        })
        if scn.edges.count > 0:
            self._write_json("edge_metrics.json", edge_metrics)
        self._write_json("loadgen_metrics.json", loadgen_metrics)
        if metrics_history is not None:
            self._write_json("metrics_history.json", metrics_history)
        if fleet_health is not None:
            self._write_json("fleet_health.json", fleet_health)
        if alerts_status is not None:
            self._write_json("alerts_status.json", alerts_status)
        if runbooks_status is not None:
            self._write_json("runbooks_status.json", runbooks_status)
        if forensics_index is not None:
            self._write_json("forensics_index.json", forensics_index)
        self._write_json("scenario_summary.json", summary)
        return summary

    def _annotate_rounds(self, records: List[dict], wall0: float) -> List[dict]:
        """Per-round digest with the phase each round *started* in
        (records carry finish-time ``wall_ts`` and ``duration_s``)."""
        out = []
        warmup = set(self.warmup_round_names)
        for r in records:
            started = float(r.get("wall_ts") or 0.0) - float(
                r.get("duration_s") or 0.0
            )
            entry = {
                "round": r.get("round"),
                "outcome": r.get("outcome"),
                "participants": r.get("participants"),
                "reporters": r.get("reporters"),
                "stragglers": len(r.get("stragglers") or ()),
                "duration_s": r.get("duration_s"),
                "warmup": r.get("round") in warmup,
            }
            if not entry["warmup"]:
                t = started - wall0
                entry["scenario_t"] = round(t, 3)
                entry["phase"] = self.scenario.phase_at(max(0.0, t))[1].name
            out.append(entry)
        return out

    def _write_json(self, name: str, obj: dict) -> None:
        with open(os.path.join(self.artifacts_dir, name), "w",
                  encoding="utf-8") as fh:
            json.dump(obj, fh, indent=2, default=repr)
            fh.write("\n")

    async def _teardown(self) -> None:
        for task in self._round_tasks:
            if not task.done():
                task.cancel()
                with contextlib.suppress(Exception, asyncio.CancelledError):
                    await task
        if self._session is not None:
            await self._session.close()
        # workers first (their cleanup pings nothing), manager last
        for runner in reversed(self._runners):
            with contextlib.suppress(Exception):
                await runner.cleanup()
        for slot in self._slots:
            await self._reap(slot)


async def run_scenario(scenario: Scenario, artifacts_dir: str,
                       tick_s: float = 0.1) -> dict:
    return await ScenarioRunner(scenario, artifacts_dir, tick_s=tick_s).run()
