"""Declarative scenario configs for the open-loop load generator.

A scenario file (``benchmarks/scenarios/*.json``) describes one
production-traffic shape as an ordered list of **phases**, each with

- an **availability curve** — what fraction of the fleet answers
  ``round_start`` at time ``t`` (``step`` holds a level, ``sine`` is a
  compressed diurnal day);
- **churn rates** — expected permanent leaves/joins per second
  (leaves stop the worker's server cold: no deregister call, the
  manager finds out via notify failures and the TTL cull);
- **faults** — :class:`baton_tpu.utils.faults.FaultInjector` rules
  installed for the phase's duration (delays, errors, connection
  drops, on the manager or on every worker);

plus fleet-wide knobs (worker count, device-speed multipliers mapped to
``train_time_scale``), manager knobs (round timeout, TTL, cohort
sampling), the open-loop round clock, the **alerts block** (the
manager's declarative alert rules — defaults to the
:mod:`baton_tpu.obs.alerts` pack; rules are validated at parse time so
a typo'd rule fails the run at load, not silently at the first
evaluation tick), and the **SLO block** the evaluator
(:mod:`baton_tpu.loadgen.slo`) gates on.

Everything here is pure config parsing + the availability math — no
I/O beyond :func:`load_scenario`, so the curve shapes are unit-testable
without spinning up a federation.

Unknown keys are an error, not a silent default: a typo'd
``"availabilty"`` must fail the run, not quietly flatten the curve.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import re
from typing import Any, Dict, List, Optional, Tuple

# pure-stdlib module (no jax, no server deps) — safe to import here
from baton_tpu.obs.alerts import AlertRule, AlertRuleError


class ScenarioError(ValueError):
    """Malformed scenario config (bad key, type, or range)."""


_NAME_RE = re.compile(r"^[A-Za-z0-9_\-]{1,64}$")

#: metric comparison operators the SLO block accepts
SLO_OPS = ("<=", ">=", "<", ">", "==")


def _take(d: Dict[str, Any], ctx: str, **fields: Any) -> Dict[str, Any]:
    """Pop known ``fields`` (name → default) out of ``d``; any leftover
    key is a config error. Returns the resolved values."""
    if not isinstance(d, dict):
        raise ScenarioError(f"{ctx}: expected an object, got {type(d).__name__}")
    out = {}
    d = dict(d)
    for key, default in fields.items():
        out[key] = d.pop(key, default)
    if d:
        raise ScenarioError(
            f"{ctx}: unknown key(s) {sorted(d)} (known: {sorted(fields)})"
        )
    return out


def _num(ctx: str, name: str, val: Any, lo: Optional[float] = None,
         hi: Optional[float] = None) -> float:
    if not isinstance(val, (int, float)) or isinstance(val, bool):
        raise ScenarioError(f"{ctx}: `{name}` must be a number, got {val!r}")
    val = float(val)
    if lo is not None and val < lo:
        raise ScenarioError(f"{ctx}: `{name}` must be >= {lo}, got {val}")
    if hi is not None and val > hi:
        raise ScenarioError(f"{ctx}: `{name}` must be <= {hi}, got {val}")
    return val


# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class AvailabilitySpec:
    """``{"kind": "step", "level": 0.8}`` or
    ``{"kind": "sine", "min": 0.3, "max": 1.0, "period_s": 20}``.

    The sine starts at its peak (``phase`` = 0.25 turns) and troughs
    mid-period — one compressed diurnal day per ``period_s``."""

    kind: str = "step"
    level: float = 1.0
    min: float = 0.0
    max: float = 1.0
    period_s: float = 60.0
    phase: float = 0.25

    @staticmethod
    def parse(d: Dict[str, Any], ctx: str) -> "AvailabilitySpec":
        f = _take(d, ctx, kind="step", level=1.0, min=0.0, max=1.0,
                  period_s=60.0, phase=0.25)
        if f["kind"] not in ("step", "sine"):
            raise ScenarioError(
                f"{ctx}: availability kind must be 'step' or 'sine', "
                f"got {f['kind']!r}"
            )
        spec = AvailabilitySpec(
            kind=f["kind"],
            level=_num(ctx, "level", f["level"], 0.0, 1.0),
            min=_num(ctx, "min", f["min"], 0.0, 1.0),
            max=_num(ctx, "max", f["max"], 0.0, 1.0),
            period_s=_num(ctx, "period_s", f["period_s"], 1e-3),
            phase=_num(ctx, "phase", f["phase"]),
        )
        if spec.kind == "sine" and spec.min > spec.max:
            raise ScenarioError(f"{ctx}: sine min > max")
        return spec

    def level_at(self, t: float) -> float:
        """Available fraction of the fleet at ``t`` seconds into the
        phase, in [0, 1]."""
        if self.kind == "step":
            return self.level
        mid = 0.5 * (self.min + self.max)
        amp = 0.5 * (self.max - self.min)
        val = mid + amp * math.sin(
            2.0 * math.pi * (t / self.period_s + self.phase)
        )
        return min(1.0, max(0.0, val))


@dataclasses.dataclass(frozen=True)
class ChurnSpec:
    leave_per_s: float = 0.0
    join_per_s: float = 0.0

    @staticmethod
    def parse(d: Dict[str, Any], ctx: str) -> "ChurnSpec":
        f = _take(d, ctx, leave_per_s=0.0, join_per_s=0.0)
        return ChurnSpec(
            leave_per_s=_num(ctx, "leave_per_s", f["leave_per_s"], 0.0),
            join_per_s=_num(ctx, "join_per_s", f["join_per_s"], 0.0),
        )


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One FaultInjector rule, installed for the phase's duration."""

    target: str          # "manager" | "workers"
    action: str          # "error" | "delay" | "drop"
    match: str           # substring of path+query (see utils/faults.py)
    status: int = 503
    delay_s: float = 0.0
    times: Optional[int] = None

    @staticmethod
    def parse(d: Dict[str, Any], ctx: str) -> "FaultSpec":
        f = _take(d, ctx, target="manager", action=None, match=None,
                  status=503, delay_s=0.0, times=None)
        if f["target"] not in ("manager", "workers"):
            raise ScenarioError(
                f"{ctx}: fault target must be 'manager' or 'workers'"
            )
        if f["action"] not in ("error", "delay", "drop"):
            raise ScenarioError(
                f"{ctx}: fault action must be 'error', 'delay', or 'drop'"
            )
        if not isinstance(f["match"], str) or not f["match"]:
            raise ScenarioError(f"{ctx}: fault `match` must be a non-empty string")
        times = f["times"]
        if times is not None and (not isinstance(times, int) or times < 1):
            raise ScenarioError(f"{ctx}: fault `times` must be a positive int")
        return FaultSpec(
            target=f["target"], action=f["action"], match=f["match"],
            status=int(_num(ctx, "status", f["status"], 100, 599)),
            delay_s=_num(ctx, "delay_s", f["delay_s"], 0.0),
            times=times,
        )


@dataclasses.dataclass(frozen=True)
class PhaseSpec:
    name: str
    duration_s: float
    availability: AvailabilitySpec
    churn: ChurnSpec
    faults: Tuple[FaultSpec, ...]
    #: edge indices (into ``edges.count``) torn down cold when this
    #: phase is entered — the edge-death chaos knob
    kill_edges: Tuple[int, ...] = ()
    #: tear down the *active root replica* cold when this phase is
    #: entered — the control-plane chaos knob. Requires
    #: ``manager.standby_roots`` ≥ the number of kill_root phases: each
    #: kill consumes one warm standby (the driver waits for lease-expiry
    #: promotion and retargets the open-loop clock at the new active).
    kill_root: bool = False

    @staticmethod
    def parse(d: Dict[str, Any], idx: int) -> "PhaseSpec":
        ctx = f"phases[{idx}]"
        f = _take(d, ctx, name=f"phase{idx}", duration_s=None,
                  availability=None, churn=None, faults=None,
                  kill_edges=None, kill_root=False)
        if not isinstance(f["name"], str) or not f["name"]:
            raise ScenarioError(f"{ctx}: `name` must be a non-empty string")
        dur = _num(ctx, "duration_s", f["duration_s"], 1e-3)
        avail = AvailabilitySpec.parse(
            f["availability"] or {}, f"{ctx}.availability"
        )
        churn = ChurnSpec.parse(f["churn"] or {}, f"{ctx}.churn")
        raw_faults = f["faults"] or []
        if not isinstance(raw_faults, list):
            raise ScenarioError(f"{ctx}: `faults` must be a list")
        faults = tuple(
            FaultSpec.parse(fd, f"{ctx}.faults[{i}]")
            for i, fd in enumerate(raw_faults)
        )
        raw_kills = f["kill_edges"] or []
        if not isinstance(raw_kills, list):
            raise ScenarioError(f"{ctx}: `kill_edges` must be a list")
        kills = tuple(
            int(_num(f"{ctx}.kill_edges[{i}]", "index", k, 0))
            for i, k in enumerate(raw_kills)
        )
        if not isinstance(f["kill_root"], bool):
            raise ScenarioError(f"{ctx}: `kill_root` must be a boolean")
        return PhaseSpec(f["name"], dur, avail, churn, faults, kills,
                         f["kill_root"])


@dataclasses.dataclass(frozen=True)
class SpeedGroup:
    """A fraction of the fleet running at ``scale`` × real train time
    (worker ``train_time_scale``). Workers not covered by any group run
    at 1.0."""

    scale: float
    fraction: float


@dataclasses.dataclass(frozen=True)
class WorkerSpec:
    count: int = 8
    heartbeat_time: float = 0.5
    batch_size: int = 32
    learning_rate: float = 0.02
    min_batches: int = 2
    max_batches: int = 2
    upload_chunk_bytes: Optional[int] = None
    speeds: Tuple[SpeedGroup, ...] = ()

    @staticmethod
    def parse(d: Dict[str, Any]) -> "WorkerSpec":
        ctx = "workers"
        f = _take(d, ctx, count=8, heartbeat_time=0.5, batch_size=32,
                  learning_rate=0.02, min_batches=2, max_batches=2,
                  upload_chunk_bytes=None, speeds=None)
        count = int(_num(ctx, "count", f["count"], 1))
        raw_speeds = f["speeds"] or []
        if not isinstance(raw_speeds, list):
            raise ScenarioError(f"{ctx}: `speeds` must be a list")
        groups, frac_total = [], 0.0
        for i, sd in enumerate(raw_speeds):
            sf = _take(sd, f"{ctx}.speeds[{i}]", scale=None, fraction=None)
            scale = _num(f"{ctx}.speeds[{i}]", "scale", sf["scale"], 1.0)
            frac = _num(f"{ctx}.speeds[{i}]", "fraction", sf["fraction"],
                        0.0, 1.0)
            frac_total += frac
            groups.append(SpeedGroup(scale=scale, fraction=frac))
        if frac_total > 1.0 + 1e-9:
            raise ScenarioError(f"{ctx}: speed fractions sum to {frac_total} > 1")
        chunk = f["upload_chunk_bytes"]
        if chunk is not None:
            chunk = int(_num(ctx, "upload_chunk_bytes", chunk, 1))
        return WorkerSpec(
            count=count,
            heartbeat_time=_num(ctx, "heartbeat_time", f["heartbeat_time"], 0.05),
            batch_size=int(_num(ctx, "batch_size", f["batch_size"], 1)),
            learning_rate=_num(ctx, "learning_rate", f["learning_rate"], 0.0),
            min_batches=int(_num(ctx, "min_batches", f["min_batches"], 1)),
            max_batches=int(_num(ctx, "max_batches", f["max_batches"], 1)),
            upload_chunk_bytes=chunk,
            speeds=tuple(groups),
        )

    def speed_for(self, idx: int) -> float:
        """Deterministic speed assignment: group g covers the next
        ``round(fraction × count)`` worker indices, remainder is 1.0.
        Joined workers keep cycling the same layout (idx mod count)."""
        idx %= max(1, self.count)
        lo = 0
        for g in self.speeds:
            n = int(round(g.fraction * self.count))
            if lo <= idx < lo + n:
                return g.scale
            lo += n
        return 1.0


@dataclasses.dataclass(frozen=True)
class EdgeSpec:
    """The hierarchical aggregation tier (``server/edge.py``): ``count``
    edge aggregators between the fleet and the root manager, workers
    assigned by consistent hash (``server/topology.py``). ``count: 0``
    (the default) is the flat topology — every worker talks to the root
    directly. ``retry_s`` is how long a worker sits on the direct
    fallback route after an edge transport failure before re-trying its
    edge."""

    count: int = 0
    flush_after_s: float = 15.0
    heartbeat_time: float = 1.0
    retry_s: float = 30.0

    @staticmethod
    def parse(d: Dict[str, Any]) -> "EdgeSpec":
        ctx = "edges"
        f = _take(d, ctx, count=0, flush_after_s=15.0, heartbeat_time=1.0,
                  retry_s=30.0)
        return EdgeSpec(
            count=int(_num(ctx, "count", f["count"], 0)),
            flush_after_s=_num(ctx, "flush_after_s", f["flush_after_s"], 0.05),
            heartbeat_time=_num(ctx, "heartbeat_time", f["heartbeat_time"],
                                0.05),
            retry_s=_num(ctx, "retry_s", f["retry_s"], 0.0),
        )


@dataclasses.dataclass(frozen=True)
class ManagerSpec:
    round_timeout: float = 6.0
    client_ttl: float = 5.0
    cohort_fraction: float = 1.0
    min_cohort: int = 1
    ingest_workers: int = 2
    streaming_aggregation: bool = True
    #: warm standby root replicas behind the active (server/replication):
    #: 0 (default) is the single-root topology. With standbys the active
    #: journals every round and ships the WAL; workers get the standby
    #: list as their ``failover`` ring.
    standby_roots: int = 0
    ha_lease_s: float = 1.0
    ha_ship_interval_s: float = 0.25
    ha_promote_grace_s: float = 0.5

    @staticmethod
    def parse(d: Dict[str, Any]) -> "ManagerSpec":
        ctx = "manager"
        f = _take(d, ctx, round_timeout=6.0, client_ttl=5.0,
                  cohort_fraction=1.0, min_cohort=1, ingest_workers=2,
                  streaming_aggregation=True, standby_roots=0,
                  ha_lease_s=1.0, ha_ship_interval_s=0.25,
                  ha_promote_grace_s=0.5)
        return ManagerSpec(
            round_timeout=_num(ctx, "round_timeout", f["round_timeout"], 0.1),
            client_ttl=_num(ctx, "client_ttl", f["client_ttl"], 0.1),
            cohort_fraction=_num(ctx, "cohort_fraction", f["cohort_fraction"],
                                 0.0, 1.0),
            min_cohort=int(_num(ctx, "min_cohort", f["min_cohort"], 1)),
            ingest_workers=int(_num(ctx, "ingest_workers", f["ingest_workers"], 0)),
            streaming_aggregation=bool(f["streaming_aggregation"]),
            standby_roots=int(_num(ctx, "standby_roots", f["standby_roots"],
                                   0)),
            ha_lease_s=_num(ctx, "ha_lease_s", f["ha_lease_s"], 0.1),
            ha_ship_interval_s=_num(ctx, "ha_ship_interval_s",
                                    f["ha_ship_interval_s"], 0.01),
            ha_promote_grace_s=_num(ctx, "ha_promote_grace_s",
                                    f["ha_promote_grace_s"], 0.0),
        )


@dataclasses.dataclass(frozen=True)
class RoundsSpec:
    """The open-loop clock: a round is *attempted* every ``interval_s``
    seconds of scenario time regardless of whether the previous one
    finished — a busy manager answers 423 and the refusal is counted,
    exactly like overload in production."""

    n_epoch: int = 1
    interval_s: float = 2.0
    max_rounds: Optional[int] = None
    warmup: int = 1
    drain_grace_s: Optional[float] = None   # default: round_timeout + 5

    @staticmethod
    def parse(d: Dict[str, Any]) -> "RoundsSpec":
        ctx = "rounds"
        f = _take(d, ctx, n_epoch=1, interval_s=2.0, max_rounds=None,
                  warmup=1, drain_grace_s=None)
        max_rounds = f["max_rounds"]
        if max_rounds is not None:
            max_rounds = int(_num(ctx, "max_rounds", max_rounds, 1))
        grace = f["drain_grace_s"]
        if grace is not None:
            grace = _num(ctx, "drain_grace_s", grace, 0.0)
        return RoundsSpec(
            n_epoch=int(_num(ctx, "n_epoch", f["n_epoch"], 1)),
            interval_s=_num(ctx, "interval_s", f["interval_s"], 0.05),
            max_rounds=max_rounds,
            warmup=int(_num(ctx, "warmup", f["warmup"], 0)),
            drain_grace_s=grace,
        )


@dataclasses.dataclass(frozen=True)
class SLOAssertion:
    """``{"metric": "rounds.completion_rate", "op": ">=", "value": 0.5}``
    — metric addressing is documented in :mod:`baton_tpu.loadgen.slo`."""

    metric: str
    op: str
    value: float

    @staticmethod
    def parse(d: Dict[str, Any], idx: int) -> "SLOAssertion":
        ctx = f"slo.assertions[{idx}]"
        f = _take(d, ctx, metric=None, op=None, value=None)
        if not isinstance(f["metric"], str) or not f["metric"]:
            raise ScenarioError(f"{ctx}: `metric` must be a non-empty string")
        if f["op"] not in SLO_OPS:
            raise ScenarioError(f"{ctx}: `op` must be one of {SLO_OPS}")
        return SLOAssertion(f["metric"], f["op"], _num(ctx, "value", f["value"]))


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    assertions: Tuple[SLOAssertion, ...] = ()
    baseline: Optional[str] = None   # resolved to an absolute path

    @staticmethod
    def parse(d: Dict[str, Any], base_dir: str) -> "SLOSpec":
        f = _take(d, "slo", assertions=None, baseline=None)
        raw = f["assertions"] or []
        if not isinstance(raw, list):
            raise ScenarioError("slo: `assertions` must be a list")
        assertions = tuple(
            SLOAssertion.parse(a, i) for i, a in enumerate(raw)
        )
        baseline = f["baseline"]
        if baseline is not None:
            if not isinstance(baseline, str) or not baseline:
                raise ScenarioError("slo: `baseline` must be a relative path")
            baseline = os.path.normpath(os.path.join(base_dir, baseline))
        return SLOSpec(assertions=assertions, baseline=baseline)


@dataclasses.dataclass(frozen=True)
class AlertsSpec:
    """The manager's alerting plane for this run. ``rules: null`` (or an
    absent block) evaluates the default pack from
    :mod:`baton_tpu.obs.alerts`; an explicit list replaces it and every
    rule is validated by :meth:`AlertRule.parse` **at scenario load** —
    an unknown key or misspelled op fails the run before any socket
    opens. ``enabled: false`` turns the evaluator off entirely."""

    enabled: bool = True
    interval_s: float = 0.25
    rounds_window: int = 8
    rules: Optional[Tuple[Dict[str, Any], ...]] = None

    @staticmethod
    def parse(d: Dict[str, Any]) -> "AlertsSpec":
        ctx = "alerts"
        f = _take(d, ctx, enabled=True, interval_s=0.25, rounds_window=8,
                  rules=None)
        raw_rules = f["rules"]
        rules: Optional[Tuple[Dict[str, Any], ...]] = None
        if raw_rules is not None:
            if not isinstance(raw_rules, list):
                raise ScenarioError(f"{ctx}: `rules` must be a list or null")
            for i, rd in enumerate(raw_rules):
                try:
                    AlertRule.parse(rd, ctx=f"{ctx}.rules[{i}]")
                except AlertRuleError as exc:
                    raise ScenarioError(str(exc)) from exc
            rules = tuple(dict(rd) for rd in raw_rules)
        return AlertsSpec(
            enabled=bool(f["enabled"]),
            interval_s=_num(ctx, "interval_s", f["interval_s"], 0.01),
            rounds_window=int(_num(ctx, "rounds_window", f["rounds_window"],
                                   1)),
            rules=rules,
        )


@dataclasses.dataclass(frozen=True)
class RunbooksSpec:
    """The manager's autonomous-runbook plane for this run. Off by
    default — actuation is opt-in per scenario. ``rules: null`` with
    ``enabled: true`` loads the default pack from
    :mod:`baton_tpu.obs.runbooks`; an explicit list replaces it and
    every rule is validated by :meth:`RunbookRule.parse` **at scenario
    load**, same contract as :class:`AlertsSpec`."""

    enabled: bool = False
    rules: Optional[Tuple[Dict[str, Any], ...]] = None

    @staticmethod
    def parse(d: Dict[str, Any]) -> "RunbooksSpec":
        ctx = "runbooks"
        f = _take(d, ctx, enabled=False, rules=None)
        raw_rules = f["rules"]
        rules: Optional[Tuple[Dict[str, Any], ...]] = None
        if raw_rules is not None:
            if not isinstance(raw_rules, list):
                raise ScenarioError(f"{ctx}: `rules` must be a list or null")
            from baton_tpu.obs.runbooks import RunbookRule, RunbookRuleError
            for i, rd in enumerate(raw_rules):
                try:
                    RunbookRule.parse(rd, ctx=f"{ctx}.rules[{i}]")
                except RunbookRuleError as exc:
                    raise ScenarioError(str(exc)) from exc
            rules = tuple(dict(rd) for rd in raw_rules)
        return RunbooksSpec(enabled=bool(f["enabled"]), rules=rules)


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    seed: int
    model_dim: int
    workers: WorkerSpec
    manager: ManagerSpec
    rounds: RoundsSpec
    phases: Tuple[PhaseSpec, ...]
    slo: SLOSpec
    edges: EdgeSpec = EdgeSpec()
    alerts: AlertsSpec = AlertsSpec()
    runbooks: RunbooksSpec = RunbooksSpec()

    @property
    def total_s(self) -> float:
        return sum(p.duration_s for p in self.phases)

    def phase_at(self, t: float) -> Tuple[int, PhaseSpec, float]:
        """(index, phase, seconds-into-phase) at scenario time ``t``;
        past the end, sticks to the final phase."""
        acc = 0.0
        for i, p in enumerate(self.phases):
            if t < acc + p.duration_s:
                return i, p, t - acc
            acc += p.duration_s
        last = len(self.phases) - 1
        return last, self.phases[last], self.phases[last].duration_s

    def availability_at(self, t: float) -> float:
        _, phase, t_in = self.phase_at(t)
        return phase.availability.level_at(t_in)


def parse_scenario(d: Dict[str, Any], base_dir: str = ".") -> Scenario:
    f = _take(d, "scenario", name=None, seed=0, model=None, workers=None,
              manager=None, rounds=None, phases=None, slo=None, edges=None,
              alerts=None, runbooks=None)
    name = f["name"]
    if not isinstance(name, str) or not _NAME_RE.match(name):
        raise ScenarioError(
            "scenario `name` must match [A-Za-z0-9_-]{1,64} "
            "(it becomes the experiment's URL prefix)"
        )
    model = _take(f["model"] or {}, "model", dim=10)
    phases_raw = f["phases"]
    if not isinstance(phases_raw, list) or not phases_raw:
        raise ScenarioError("scenario needs a non-empty `phases` list")
    edges = EdgeSpec.parse(f["edges"] or {})
    manager = ManagerSpec.parse(f["manager"] or {})
    phases = tuple(PhaseSpec.parse(p, i) for i, p in enumerate(phases_raw))
    for i, p in enumerate(phases):
        for k in p.kill_edges:
            if k >= edges.count:
                raise ScenarioError(
                    f"phases[{i}]: kill_edges index {k} out of range "
                    f"(edges.count = {edges.count})"
                )
    n_root_kills = sum(1 for p in phases if p.kill_root)
    if n_root_kills > manager.standby_roots:
        raise ScenarioError(
            f"{n_root_kills} kill_root phase(s) but manager.standby_roots = "
            f"{manager.standby_roots} — each root kill consumes one warm "
            f"standby"
        )
    if manager.standby_roots > 0 and edges.count > 0:
        raise ScenarioError(
            "manager.standby_roots with an edge tier is not supported yet "
            "(edges have no root-failover ring)"
        )
    return Scenario(
        name=name,
        seed=int(_num("scenario", "seed", f["seed"])),
        model_dim=int(_num("model", "dim", model["dim"], 1)),
        workers=WorkerSpec.parse(f["workers"] or {}),
        manager=manager,
        rounds=RoundsSpec.parse(f["rounds"] or {}),
        phases=phases,
        slo=SLOSpec.parse(f["slo"] or {}, base_dir),
        edges=edges,
        alerts=AlertsSpec.parse(f["alerts"] or {}),
        runbooks=RunbooksSpec.parse(f["runbooks"] or {}),
    )


def load_scenario(path: str) -> Scenario:
    """Parse a scenario file; ``slo.baseline`` resolves relative to it."""
    with open(path, "r", encoding="utf-8") as fh:
        try:
            data = json.load(fh)
        except ValueError as exc:
            raise ScenarioError(f"{path}: not valid JSON: {exc}") from exc
    return parse_scenario(data, base_dir=os.path.dirname(os.path.abspath(path)))
