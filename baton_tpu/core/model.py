"""Model contract for baton_tpu.

The reference's model contract is stateful PyTorch: ``state_dict()`` /
``load_state_dict()`` / ``train(*data, n_epoch=...) -> loss_history``
(reference: demo.py:15-49, worker.py:98,105, manager.py:123-126). The
TPU-native contract replaces it with pure functions over pytrees so that
local training can be jit-compiled, vmapped over a client axis, and
sharded over a device mesh:

  * ``init(rng) -> params``                       (replaces nn.Module ctor)
  * ``apply(params, batch, rng) -> outputs``      (replaces forward)
  * ``per_example_loss(params, batch, rng) -> [B]`` per-example losses

Per-example (rather than mean) losses are the contract on purpose: the
framework needs them for (a) exact sample-count masking of padded batches
— the sample-weighted FedAvg math (reference manager.py:119-126) demands
exact ``n_samples`` bookkeeping — and (b) per-example gradient clipping
for DP-SGD, which is a vmap over the same function.

Batches are dicts of arrays with a shared leading batch dimension, e.g.
``{"x": f32[B, ...], "y": ...[B, ...]}``. An optional ``"mask"`` entry
(f32[B], 1.0 = real sample) is consumed by the *framework*, never by the
model: losses/grads from masked-out rows are zeroed exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping, Optional

import jax
import jax.numpy as jnp

Params = Any  # a pytree of arrays
Batch = Mapping[str, Any]
PRNGKey = jax.Array


@dataclasses.dataclass(frozen=True)
class FedModel:
    """A federated model: pure init/apply/per-example-loss functions.

    ``name`` mirrors the reference's ``model.name`` attribute used to
    derive experiment names (reference: manager.py:16, worker.py:14-16).
    """

    init: Callable[[PRNGKey], Params]
    apply: Callable[[Params, Batch, PRNGKey], Any]
    per_example_loss: Callable[[Params, Batch, PRNGKey], jax.Array]
    name: str = "fedmodel"
    # hashable model metadata (e.g. LoraSpec) — FedModel rides inside
    # jit-static trainer fields, so anything here must hash/eq by value
    aux: Any = None

    def masked_loss(self, params: Params, batch: Batch, rng: PRNGKey) -> jax.Array:
        """Mean loss over *real* (unmasked) examples.

        Fixes the reference's biased running mean (utils.py:70-91 — see
        SURVEY §2.6): this is the exact weighted mean, and all-padding
        batches contribute 0 with a guarded denominator.
        """
        losses = self.per_example_loss(params, batch, rng)
        mask = batch.get("mask")
        if mask is None:
            return jnp.mean(losses)
        mask = mask.astype(losses.dtype)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.sum(losses * mask) / denom

    def loss_and_count(self, params: Params, batch: Batch, rng: PRNGKey):
        """Returns (sum of masked losses, number of real examples).

        Summing (rather than averaging) per batch lets callers form exact
        sample-weighted epoch means regardless of ragged final batches.
        """
        losses = self.per_example_loss(params, batch, rng)
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones_like(losses)
        mask = mask.astype(losses.dtype)
        return jnp.sum(losses * mask), jnp.sum(mask)

    @classmethod
    def from_flax(
        cls,
        module: Any,
        per_example_loss: Callable[[Any, Batch, PRNGKey], jax.Array],
        example_batch: Batch,
        name: Optional[str] = None,
    ) -> "FedModel":
        """Wrap a ``flax.linen.Module`` whose ``__call__(x)`` returns logits.

        ``per_example_loss(apply_out, batch, rng)`` maps model outputs to
        per-example losses (see :mod:`baton_tpu.core.losses`).

        Modules must be stateless (no BatchNorm running stats): federated
        aggregation of BN statistics is ill-defined under client drift, so
        the model zoo uses GroupNorm/LayerNorm throughout (the standard
        FL practice). A module carrying a ``batch_stats`` collection is
        rejected at init.
        """
        x = example_batch["x"]

        def init(rng: PRNGKey) -> Params:
            variables = module.init(rng, x)
            if "batch_stats" in variables:
                raise ValueError(
                    "module carries BatchNorm running stats; use GroupNorm/"
                    "LayerNorm for federated models (BN stats don't aggregate)"
                )
            return variables

        def apply(params: Params, batch: Batch, rng: PRNGKey):
            return module.apply(params, batch["x"])

        def loss(params: Params, batch: Batch, rng: PRNGKey) -> jax.Array:
            return per_example_loss(apply(params, batch, rng), batch, rng)

        return cls(
            init=init,
            apply=apply,
            per_example_loss=loss,
            name=name or type(module).__name__.lower(),
        )
