"""Trainable/frozen parameter partitioning.

The reference trains and ships every tensor in ``state_dict()`` every
round (manager.py:77-86, 119-126). For fine-tuning workloads (LoRA,
BASELINE configs 3-4) that is untenable on TPU: vmapping full Llama-class
params over a client axis multiplies them by C. A :class:`ParamPartition`
splits a param pytree into a *trainable* part (per-client, optimized,
aggregated) and a *frozen* part (replicated once, shared by every
simulated client) by a predicate over tree paths.

Both halves are plain lists of leaves (lists are pytrees), so split and
merge are jit-transparent and structure-exact by construction.
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax

Params = Any
PathPredicate = Callable[[str, Any], bool]


def path_str(path) -> str:
    """Render a jax key path as ``a/b/0``."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


class ParamPartition:
    """Split/merge a fixed pytree structure by a per-leaf boolean mask.

    Identity-hashed on purpose: instances ride inside jit-static trainer
    fields, and two partitions are interchangeable only if they came from
    the same construction site.
    """

    def __init__(self, treedef, mask: Tuple[bool, ...],
                 paths: Tuple[str, ...] = ()):
        self.treedef = treedef
        self.mask = tuple(mask)
        self.n_trainable = sum(self.mask)
        # original tree paths per leaf (same order as mask) — lets layout
        # code (e.g. tensor-parallel sharding of the frozen base) recover
        # leaf identities that the flat split lists erase
        self.paths = tuple(paths)

    @property
    def frozen_paths(self) -> Tuple[str, ...]:
        return tuple(p for p, m in zip(self.paths, self.mask) if not m)

    @property
    def trainable_paths(self) -> Tuple[str, ...]:
        return tuple(p for p, m in zip(self.paths, self.mask) if m)

    def split(self, params: Params) -> Tuple[List, List]:
        leaves = jax.tree_util.tree_leaves(params)
        if len(leaves) != len(self.mask):
            raise ValueError(
                f"params have {len(leaves)} leaves, partition expects "
                f"{len(self.mask)}"
            )
        trainable = [l for l, m in zip(leaves, self.mask) if m]
        frozen = [l for l, m in zip(leaves, self.mask) if not m]
        return trainable, frozen

    def merge(self, trainable: List, frozen: List) -> Params:
        if trainable is None or frozen is None:
            raise ValueError(
                "partition.merge needs both halves; a partition-configured "
                "trainer must be passed the frozen leaves explicitly"
            )
        n_frozen = len(self.mask) - self.n_trainable
        if len(trainable) != self.n_trainable or len(frozen) != n_frozen:
            raise ValueError(
                f"expected {self.n_trainable} trainable + {n_frozen} frozen "
                f"leaves, got {len(trainable)} + {len(frozen)}"
            )
        t, f = iter(trainable), iter(frozen)
        leaves = [next(t) if m else next(f) for m in self.mask]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


def make_partition(params: Params, predicate: PathPredicate) -> ParamPartition:
    """Build a partition: ``predicate(path_str, leaf)`` True = trainable."""
    path_leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
    mask = tuple(bool(predicate(path_str(p), l)) for p, l in path_leaves)
    if not any(mask):
        raise ValueError("partition selects no trainable leaves")
    return ParamPartition(treedef, mask,
                          paths=tuple(path_str(p) for p, _ in path_leaves))
