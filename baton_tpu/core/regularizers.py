"""Pluggable local-objective regularizers.

The reference's local objective is whatever the user's ``model.train``
does (demo.py:29-49) — there is no regularization hook. Here the local
objective is ``data_loss + regularizer(params, anchor)`` where ``anchor``
is the round's broadcast global params (see
:class:`baton_tpu.core.training.LocalTrainer`), which is exactly the
shape FedProx needs.
"""

from __future__ import annotations

import jax.numpy as jnp

from baton_tpu.ops.aggregation import global_sq_dist


def fedprox(mu: float):
    """FedProx proximal term ``(mu/2)·‖params − global‖²`` (Li et al.,
    MLSys 2020). Tames client drift under non-IID shards and stragglers;
    BASELINE config 3 (BERT/AG-News federated fine-tune) uses it."""

    def reg(params, anchor):
        return 0.5 * jnp.float32(mu) * global_sq_dist(params, anchor)

    return reg
