"""Jitted local training — the TPU replacement for the reference hot loop.

The reference's local training is a Python for-loop over epochs and
batches doing zero_grad/forward/MSE/backward/step on the worker's event
loop (reference: demo.py:29-49, worker.py:103-106 — it even blocks
heartbeats, SURVEY §2.9 item 7). Here the *entire* multi-epoch run is one
XLA program: ``lax.scan`` over epochs, ``lax.scan`` over batches, optax
update inline — so it can be vmapped over thousands of simulated clients
and sharded over a TPU mesh with zero Python in the hot path.

Static-shape discipline (XLA): client datasets are padded to a fixed
``capacity`` divisible by ``batch_size``; a per-row validity mask derived
from the *dynamic* ``n_samples`` scalar zeroes the loss/grad contribution
of padding exactly. Shuffling is a ``jax.random.permutation`` of row
indices per epoch (replaces torch.randperm, demo.py:33).

Loss accounting fixes the reference's biased running mean (utils.py:85-88,
SURVEY §2.6): per-epoch loss is the exact sample-weighted mean
``Σ loss_i / n_samples`` over real examples.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.experimental import io_callback

from baton_tpu.core.model import Batch, FedModel, Params, PRNGKey
from baton_tpu.core.partition import ParamPartition
from baton_tpu.ops.privacy import DPConfig, dp_sgd_grads

Regularizer = Callable[[Params, Params], jax.Array]


def num_batches(capacity: int, batch_size: int) -> int:
    if capacity % batch_size != 0:
        raise ValueError(
            f"padded capacity {capacity} must be divisible by batch_size {batch_size}; "
            "use baton_tpu.ops.padding.pad_dataset"
        )
    return capacity // batch_size


@dataclasses.dataclass(frozen=True)
class LocalTrainer:
    """Compiled multi-epoch local training for one client.

    ``train(params, data, n_samples, rng, n_epochs)`` returns
    ``(params, opt_state, loss_history[n_epochs])``. ``data`` is a dict of
    arrays padded to a static capacity; ``n_samples`` is the dynamic count
    of real rows (the same number that weights this client in FedAvg,
    reference manager.py:119-126).

    When ``regularizer`` is set, ``train`` takes an ``anchor`` params
    pytree and the local objective becomes ``data_loss + regularizer(
    params, anchor)`` — the pluggable local-objective hook used for
    FedProx (anchor = the round's global params).

    When ``partition`` is set, ``params`` is only the *trainable* leaf
    list and the ``frozen`` leaf list must be supplied; the model sees
    ``partition.merge(params, frozen)`` while gradients, optimizer state,
    and the FedAvg payload stay trainable-only (LoRA fine-tuning: clients
    carry adapters, never the base model).
    """

    model: FedModel
    optimizer: optax.GradientTransformation
    batch_size: int
    regularizer: Optional[Regularizer] = None
    partition: Optional[ParamPartition] = None
    # example-level DP-SGD (ops/privacy.py): per-example clipping +
    # Gaussian noise replace the plain batch gradient when set
    dp: Optional[DPConfig] = None
    # Mid-training visibility (the reference streams tqdm batch progress
    # and a running loss during local training, reference utils.py:70-91,
    # demo.py:37-38; a jitted multi-epoch run is otherwise a black box).
    # When set, ``progress_fn(epoch_index, epoch_loss)`` fires on the HOST
    # after each epoch via ``jax.experimental.io_callback`` — the TPU-way
    # equivalent of the reference's progress bar. Ordered, so it is for
    # the single-client path (the HTTP worker, the manager's simulated
    # cohort participant); leave unset under vmap/shard_map.
    progress_fn: Optional[Callable[[int, float], None]] = None

    def init_opt_state(self, params: Params):
        return self.optimizer.init(params)

    # -- compute-plane accounting (baton_tpu/obs/compute.py) -----------
    def train_signature(self, data: Batch, n_epochs: int) -> tuple:
        """The jit-cache shape signature of one ``train`` call: data
        shapes/dtypes plus the static epoch count. A signature the
        compute probe's :class:`~baton_tpu.obs.compute.CompileTracker`
        has not seen means XLA compiled during that call."""
        shapes = tuple(sorted(
            (k, tuple(v.shape), str(getattr(v, "dtype", type(v).__name__)))
            for k, v in data.items()
        ))
        return (shapes, int(n_epochs), int(self.batch_size))

    def steps_per_round(self, capacity: int, n_epochs: int) -> int:
        """Optimizer steps one ``train`` call executes on device: the
        scan runs every padded batch every epoch (masked no-ops included
        — they still cost the FLOPs)."""
        return int(n_epochs) * num_batches(int(capacity), self.batch_size)

    # donation decided no: params is the caller's broadcast anchor —
    # the engine re-reads it for every client in the wave
    @partial(jax.jit, static_argnums=(0, 5))  # batonlint: allow[BTL011]
    def train(
        self,
        params: Params,
        data: Batch,
        n_samples: jax.Array,
        rng: PRNGKey,
        n_epochs: int,
        anchor: Optional[Params] = None,
        frozen: Optional[Params] = None,
    ):
        opt_state = self.optimizer.init(params)
        return self.train_with_opt_state(
            params, opt_state, data, n_samples, rng, n_epochs, anchor, frozen
        )

    @partial(jax.jit, static_argnums=(0, 6), donate_argnums=(2,))
    def train_with_opt_state(
        self,
        params: Params,
        opt_state,
        data: Batch,
        n_samples: jax.Array,
        rng: PRNGKey,
        n_epochs: int,
        anchor: Optional[Params] = None,
        frozen: Optional[Params] = None,
    ):
        """Same as ``train`` but threads optimizer state (for stateful
        local optimizers persisted across rounds, or wave scheduling)."""
        leaves = jax.tree_util.tree_leaves(data)
        capacity = leaves[0].shape[0]
        nb = num_batches(capacity, self.batch_size)
        n_samples = jnp.asarray(n_samples, jnp.int32)

        def merged(p):
            return self.partition.merge(p, frozen) if self.partition else p

        def objective(p, batch, step_rng):
            loss_sum, count = self.model.loss_and_count(merged(p), batch, step_rng)
            denom = jnp.maximum(count, 1.0)
            loss = loss_sum / denom
            if self.regularizer is not None:
                loss = loss + self.regularizer(p, anchor)
            return loss, (loss_sum, count)

        grad_fn = jax.value_and_grad(objective, has_aux=True)

        def masked_loss_sum(p, batch, step_rng):
            """Masked data-loss sum only (no regularizer) — the per-example
            clipping target for DP-SGD; padding rows contribute exactly 0."""
            s, _ = self.model.loss_and_count(merged(p), batch, step_rng)
            return s

        def batch_step(carry, batch):
            p, os, step_rng = carry
            step_rng, sub = jax.random.split(step_rng)
            if self.dp is not None:
                grads, ex_losses = dp_sgd_grads(
                    masked_loss_sum, p, batch, sub, self.dp, self.batch_size
                )
                if self.regularizer is not None:
                    # the prox term is data-independent: its gradient is
                    # exact (un-noised) and consumes no privacy budget
                    reg_grads = jax.grad(
                        lambda q: self.regularizer(q, anchor)
                    )(p)
                    grads = jax.tree_util.tree_map(
                        lambda g, r: (g + r).astype(g.dtype), grads, reg_grads
                    )
                # ex_losses are already mask-zeroed (masked_loss_sum);
                # NOT privatized — see DPConfig docstring
                loss_sum = jnp.sum(ex_losses)
                count = jnp.sum(batch["mask"].astype(jnp.float32))
            else:
                (_, (loss_sum, count)), grads = grad_fn(p, batch, sub)
            # An all-padding batch yields exactly-zero grads; gate the
            # update so stateful optimizers (momentum/adam) don't mutate
            # state on phantom steps.
            nonempty = count > 0
            updates, new_os = self.optimizer.update(grads, os, p)
            new_p = optax.apply_updates(p, updates)
            p = jax.tree_util.tree_map(
                lambda new, old: jnp.where(nonempty, new, old), new_p, p
            )
            os = jax.tree_util.tree_map(
                lambda new, old: jnp.where(nonempty, new, old), new_os, os
            )
            return (p, os, step_rng), (loss_sum, count)

        def epoch_step(carry, xs):
            epoch_rng, epoch_idx = xs
            p, os = carry
            perm_rng, step_rng = jax.random.split(epoch_rng)
            perm = jax.random.permutation(perm_rng, capacity)
            mask = (perm < n_samples).astype(jnp.float32)
            shuffled = jax.tree_util.tree_map(lambda a: jnp.take(a, perm, axis=0), data)
            shuffled = dict(shuffled)
            if "mask" in shuffled:
                mask = mask * shuffled["mask"].astype(jnp.float32)
            shuffled["mask"] = mask
            batched = jax.tree_util.tree_map(
                lambda a: a.reshape((nb, self.batch_size) + a.shape[1:]), shuffled
            )
            (p, os, _), (loss_sums, counts) = jax.lax.scan(
                batch_step, (p, os, step_rng), batched
            )
            total = jnp.maximum(jnp.sum(counts), 1.0)
            epoch_loss = jnp.sum(loss_sums) / total
            if self.progress_fn is not None:
                io_callback(
                    self.progress_fn, None, epoch_idx, epoch_loss,
                    ordered=True,
                )
            return (p, os), epoch_loss

        epoch_rngs = jax.random.split(rng, n_epochs)
        (params, opt_state), loss_history = jax.lax.scan(
            epoch_step,
            (params, opt_state),
            (epoch_rngs, jnp.arange(n_epochs, dtype=jnp.int32)),
        )
        return params, opt_state, loss_history


def make_local_trainer(
    model: FedModel,
    optimizer: Optional[optax.GradientTransformation] = None,
    batch_size: int = 32,
    learning_rate: float = 1e-3,
    regularizer: Optional[Regularizer] = None,
    partition: Optional[ParamPartition] = None,
    dp: Optional[DPConfig] = None,
    progress_fn: Optional[Callable[[int, float], None]] = None,
) -> LocalTrainer:
    """Build a :class:`LocalTrainer`.

    Defaults mirror the reference demo: SGD, lr=0.001, batch_size=32
    (reference: demo.py:29,34).
    """
    if optimizer is None:
        optimizer = optax.sgd(learning_rate)
    return LocalTrainer(
        model=model,
        optimizer=optimizer,
        batch_size=batch_size,
        regularizer=regularizer,
        partition=partition,
        dp=dp,
        progress_fn=progress_fn,
    )


def make_evaluator(model: FedModel):
    """Jitted full-dataset evaluation: mean loss (+accuracy for int labels).
    The whole eval set goes through one apply; shard or chunk large sets
    at the call site."""

    # donation decided no: evaluation never owns its inputs
    @jax.jit  # batonlint: allow[BTL011]
    def evaluate(params: Params, data: Batch, rng: PRNGKey):
        losses = model.per_example_loss(params, data, rng)
        mask = data.get("mask")
        if mask is None:
            mask = jnp.ones_like(losses)
        mask = mask.astype(jnp.float32)
        denom = jnp.maximum(mask.sum(), 1.0)
        out = {"loss": jnp.sum(losses * mask) / denom}
        y = data.get("y")
        if y is not None and jnp.issubdtype(y.dtype, jnp.integer):
            logits = model.apply(params, data, rng)
            correct = (jnp.argmax(logits, axis=-1) == y).astype(jnp.float32)
            out["accuracy"] = jnp.sum(correct * mask) / denom
        return out

    return evaluate
