"""Per-example loss builders.

The reference hard-codes ``nn.MSELoss`` in the demo training loop
(reference: demo.py:31,44). Here losses are pluggable, per-example (see
:mod:`baton_tpu.core.model` for why), and written so XLA fuses them into
the backward matmuls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def mse(outputs: jax.Array, batch, rng) -> jax.Array:
    """Per-example mean-squared error. outputs [B, ...], batch["y"] same."""
    y = batch["y"]
    if outputs.ndim > y.ndim:
        outputs = outputs.squeeze(-1)
    err = (outputs - y).astype(jnp.float32)
    if err.ndim == 1:
        return err * err
    return jnp.mean(err * err, axis=tuple(range(1, err.ndim)))


def softmax_cross_entropy(logits: jax.Array, batch, rng) -> jax.Array:
    """Per-example cross entropy with integer labels batch["y"] [B]."""
    labels = batch["y"]
    logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    label_logits = jnp.take_along_axis(
        logits.astype(jnp.float32), labels[..., None], axis=-1
    ).squeeze(-1)
    return logz - label_logits


def sigmoid_binary_cross_entropy(logits: jax.Array, batch, rng) -> jax.Array:
    """Per-example binary cross entropy, batch["y"] in {0,1} [B]."""
    y = batch["y"].astype(jnp.float32)
    logits = logits.astype(jnp.float32)
    if logits.ndim > y.ndim:
        logits = logits.squeeze(-1)
    return jnp.maximum(logits, 0) - logits * y + jnp.log1p(jnp.exp(-jnp.abs(logits)))
