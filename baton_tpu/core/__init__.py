from baton_tpu.core.model import FedModel
from baton_tpu.core.training import LocalTrainer, make_local_trainer

__all__ = ["FedModel", "LocalTrainer", "make_local_trainer"]
