from baton_tpu.core.model import FedModel
from baton_tpu.core.training import LocalTrainer, make_local_trainer
from baton_tpu.core.partition import ParamPartition, make_partition
from baton_tpu.core.regularizers import fedprox

__all__ = [
    "FedModel",
    "LocalTrainer",
    "make_local_trainer",
    "ParamPartition",
    "make_partition",
    "fedprox",
]
