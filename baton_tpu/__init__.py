"""baton_tpu — a TPU-native (JAX/XLA) federated-learning framework.

Capabilities mirror the reference runtime ``mynameisfiber/baton``
(/root/reference): a manager orchestrates training *rounds* across elastic
clients; each client trains the global model locally on private data; the
manager combines results with sample-weighted FedAvg
(reference: manager.py:113-132).

Design stance (not a port): the core is a TPU-resident *simulation engine*
in which a "client" is an index along a sharded mesh axis, not a process.
Local training is a jit-compiled ``lax.scan`` train loop vmapped over the
client axis; the round broadcast is parameter replication; FedAvg is a
``psum`` of sample-weighted parameter sums over ICI. The HTTP control
plane (``baton_tpu.server``) is retained at the edge for real external
clients and reference-protocol compatibility.

Layout:
  core/      model contract + jitted local training
  ops/       aggregation kernels + ragged-data padding
  parallel/  mesh helpers + the simulation engine
  models/    model zoo (linear, MLP, CNN, ...)
  data/      synthetic data + IID/Dirichlet partitioners
"""

__version__ = "0.1.0"

from baton_tpu.core.model import FedModel  # noqa: F401
from baton_tpu.core.training import LocalTrainer, make_local_trainer  # noqa: F401
from baton_tpu.ops.aggregation import weighted_tree_mean  # noqa: F401
from baton_tpu.parallel.engine import FedSim, RoundResult  # noqa: F401
