"""Observability planes shared by bench tooling and the live fleet.

``baton_tpu.obs.compute`` is the shared probe behind bench.py's offline
numbers AND the live round loop's per-round compute records (worker →
edge → manager → ``rounds.jsonl`` → fleet ledger → SLO gate → ops
console).

``baton_tpu.obs.alerts`` watches those measurements: declarative alert
rules (threshold or multi-window burn-rate) evaluated per node with a
pending→firing→resolved lifecycle into ``alerts.jsonl``, and
``baton_tpu.obs.forensics`` packages the deep evidence a firing
``capture: true`` rule arms — profiler trace, task stacks, loop-lag,
fleet slice, round trace, metric history — into content-addressed
bundles served over HTTP.
"""
