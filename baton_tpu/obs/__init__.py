"""Compute-plane observability: live MFU / compile / HBM telemetry.

``baton_tpu.obs.compute`` is the shared probe behind bench.py's offline
numbers AND the live round loop's per-round compute records (worker →
edge → manager → ``rounds.jsonl`` → fleet ledger → SLO gate → ops
console).
"""
