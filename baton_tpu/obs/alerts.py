"""Alerting plane: declarative rules over the live metric namespace.

The system *measures* everything — round traces, per-client health,
per-round MFU/compile/HBM — but until this module nothing *watched*
the measurements: ``utils/metrics.py`` justifies its name registry by
"dashboards and alert rules", yet no alert rule was ever evaluable.
:class:`AlertEngine` closes that gap with rules **as data** evaluated
in-process by a ``PeriodicTask`` on the manager and every edge.

A rule selects one metric from the node's own flattened namespace —
the same addressing scheme :mod:`baton_tpu.loadgen.slo` uses:

``counter:<name>`` / ``gauge:<name>``
    straight from the node's :meth:`Metrics.snapshot` (counters are
    absence-is-zero, exactly like the SLO evaluator);
``timer:<name>:<stat>``
    histogram stats, ``<stat>`` in ``count``/``mean``/``p50``/``p95``/
    ``p99``/``max`` (e.g. ``timer:loop_lag_s:p95``);
``rounds.<derived>``
    derived from the tail of the node's ``rounds.jsonl`` stream (the
    manager mirrors every appended record into a bounded deque so the
    evaluator never does blocking file IO on the loop): ``tail``,
    ``straggler_rate``, ``duration_p95``, ``duration_p95_ratio``
    (recent-half p95 over older-half p95 — the regression detector),
    ``recompile_storm_rounds``, ``mfu_mean``, ``mfu_ratio``
    (recent-half mean over older-half — falling means degrading).

Rules compare with a scalar ``threshold`` or a multi-window
**burn-rate pair** (Google SRE Workbook): a counter's per-second rate
over a short AND a long window, both of which must breach before the
rule trips — the short window gives fast detection, the long window
vetoes blips. Windowed rates come from the node's metrics-history ring.

Lifecycle per rule: ``ok → pending → firing → resolved(→ok)``.
``for_s`` holds a breach in ``pending`` before it may fire (transient
spikes never page); hysteresis resolves only when the value *clearly*
recovers (``clear_ratio`` scales the threshold, so flapping around the
line stays one firing episode); ``cooldown_s`` after a resolve
suppresses an immediate re-fire. Every transition is appended to
``alerts.jsonl`` with the same single-``write()``+flush crash-safety as
``rounds.jsonl``, and the engine exports ``alerts_*`` gauges/counters.

Rules marked ``capture: true`` invoke the engine's ``on_capture`` hook
when they fire (rate-limited per rule by ``cooldown_s``) — the manager
uses it to arm a forensics bundle for the next round
(:mod:`baton_tpu.obs.forensics`).

The evaluator is an **advisory plane**: like the fleet ledger, a
failure inside rule resolution or the evaluation tick must never break
round completion. Per-rule resolution errors are counted
(``alerts_eval_errors``) and surfaced as ``skip_reason`` in the status
snapshot; the owning tick wraps the whole evaluation in try/except.

Pure stdlib; imports nothing from ``server/`` so it unit-tests without
a federation.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import (
    Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple,
)

__all__ = [
    "AlertRule",
    "AlertRuleError",
    "AlertEngine",
    "DEFAULT_RULES",
    "ALERT_OPS",
    "ALERT_SEVERITIES",
    "TIMER_STATS",
    "build_metric_view",
    "derive_rounds_tail",
    "resolve_view_metric",
    "windowed_rate",
    "read_alerts_jsonl",
]

#: comparators a rule may use (breach when ``value <op> threshold``)
ALERT_OPS = (">", ">=", "<", "<=", "==")
ALERT_SEVERITIES = ("info", "warn", "page")

#: timer stat address suffix -> snapshot key (same table as loadgen/slo)
TIMER_STATS = {
    "count": "count",
    "mean": "mean_s",
    "p50": "p50_s",
    "p95": "p95_s",
    "p99": "p99_s",
    "max": "max_s",
}

#: namespace prefixes where an absent address means "never incremented",
#: i.e. resolves to 0.0 — identical rule to loadgen/slo.resolve_metric
_ZERO_DEFAULT_PREFIXES = ("counter:",)


class AlertRuleError(ValueError):
    """A rule dict failed validation (unknown key, bad op, no
    threshold) — raised at parse time so a typo'd rule pack fails the
    process start, not silently at the first evaluation."""


@dataclass
class AlertRule:
    """One declarative rule. Build via :meth:`parse` (strict: unknown
    keys are errors, the BTL033 class of typo fails loudly)."""

    name: str
    metric: str
    op: str = ">"
    threshold: Optional[float] = None
    #: multi-window burn-rate pair: ``{"short_s", "long_s", "threshold"}``
    #: — the metric must be a ``counter:`` address; the rule breaches
    #: only when the counter's per-second rate over BOTH windows is
    #: ``op`` the pair's threshold
    burn_rate: Optional[dict] = None
    for_s: float = 0.0
    cooldown_s: float = 60.0
    severity: str = "warn"
    capture: bool = False
    #: hysteresis: while firing, the rule resolves only when the value
    #: stops breaching ``threshold * clear_ratio``; defaults to 0.9 for
    #: upper-bound ops and 1/0.9 for lower-bound ops (``==`` gets 1.0)
    clear_ratio: Optional[float] = None
    description: str = ""

    _KEYS = ("name", "metric", "op", "threshold", "burn_rate", "for_s",
             "cooldown_s", "severity", "capture", "clear_ratio",
             "description")

    @staticmethod
    def parse(d: dict, ctx: str = "alert rule") -> "AlertRule":
        if not isinstance(d, dict):
            raise AlertRuleError(f"{ctx}: rule must be an object, got "
                                 f"{type(d).__name__}")
        unknown = sorted(set(d) - set(AlertRule._KEYS))
        if unknown:
            raise AlertRuleError(f"{ctx}: unknown keys {unknown} "
                                 f"(known: {list(AlertRule._KEYS)})")
        name = d.get("name")
        metric = d.get("metric")
        if not (isinstance(name, str) and name):
            raise AlertRuleError(f"{ctx}: `name` must be a non-empty string")
        if not (isinstance(metric, str) and metric):
            raise AlertRuleError(f"{ctx} {name!r}: `metric` must be a "
                                 f"non-empty string")
        op = d.get("op", ">")
        if op not in ALERT_OPS:
            raise AlertRuleError(f"{ctx} {name!r}: op {op!r} not in "
                                 f"{ALERT_OPS}")
        severity = d.get("severity", "warn")
        if severity not in ALERT_SEVERITIES:
            raise AlertRuleError(f"{ctx} {name!r}: severity {severity!r} "
                                 f"not in {ALERT_SEVERITIES}")
        threshold = d.get("threshold")
        burn = d.get("burn_rate")
        if (threshold is None) == (burn is None):
            raise AlertRuleError(f"{ctx} {name!r}: exactly one of "
                                 f"`threshold` or `burn_rate` is required")
        if burn is not None:
            if not isinstance(burn, dict):
                raise AlertRuleError(f"{ctx} {name!r}: burn_rate must be "
                                     f"an object")
            missing = sorted(
                {"short_s", "long_s", "threshold"} - set(burn)
            )
            extra = sorted(
                set(burn) - {"short_s", "long_s", "threshold"}
            )
            if missing or extra:
                raise AlertRuleError(
                    f"{ctx} {name!r}: burn_rate needs exactly "
                    f"short_s/long_s/threshold "
                    f"(missing {missing}, unknown {extra})")
            if not float(burn["short_s"]) < float(burn["long_s"]):
                raise AlertRuleError(f"{ctx} {name!r}: burn_rate short_s "
                                     f"must be < long_s")
            if not metric.startswith("counter:"):
                raise AlertRuleError(
                    f"{ctx} {name!r}: burn_rate rules need a `counter:` "
                    f"metric address, got {metric!r}")
        clear = d.get("clear_ratio")
        if clear is not None and not float(clear) > 0:
            raise AlertRuleError(f"{ctx} {name!r}: clear_ratio must be > 0")
        return AlertRule(
            name=name,
            metric=metric,
            op=op,
            threshold=None if threshold is None else float(threshold),
            burn_rate=None if burn is None else {
                "short_s": float(burn["short_s"]),
                "long_s": float(burn["long_s"]),
                "threshold": float(burn["threshold"]),
            },
            for_s=max(0.0, float(d.get("for_s", 0.0))),
            cooldown_s=max(0.0, float(d.get("cooldown_s", 60.0))),
            severity=severity,
            capture=bool(d.get("capture", False)),
            clear_ratio=None if clear is None else float(clear),
            description=str(d.get("description", "")),
        )

    # -- comparison ----------------------------------------------------
    def _effective_threshold(self) -> float:
        return (self.burn_rate["threshold"] if self.burn_rate is not None
                else self.threshold)

    def _clear_threshold(self) -> float:
        thr = self._effective_threshold()
        ratio = self.clear_ratio
        if ratio is None:
            if self.op in (">", ">="):
                ratio = 0.9
            elif self.op in ("<", "<="):
                ratio = 1.0 / 0.9
            else:
                ratio = 1.0
        return thr * ratio

    def _cmp(self, value: float, threshold: float) -> bool:
        if self.op == ">":
            return value > threshold
        if self.op == ">=":
            return value >= threshold
        if self.op == "<":
            return value < threshold
        if self.op == "<=":
            return value <= threshold
        return value == threshold

    def breaches(self, value: Any) -> bool:
        """Does ``value`` trip the rule? Burn-rate values are
        ``{"short": rate, "long": rate}`` and BOTH windows must trip."""
        thr = self._effective_threshold()
        if self.burn_rate is not None:
            return (self._cmp(float(value["short"]), thr)
                    and self._cmp(float(value["long"]), thr))
        return self._cmp(float(value), thr)

    def still_breaching(self, value: Any) -> bool:
        """The hysteresis comparison used while FIRING: the alert holds
        until the value stops breaching the *clear* threshold, so a
        flap that dips just under the trigger line does not resolve.
        Burn-rate rules clear on the short window (it recovers first)."""
        clear = self._clear_threshold()
        if self.burn_rate is not None:
            return self._cmp(float(value["short"]), clear)
        return self._cmp(float(value), clear)


#: the default rule pack every node evaluates unless the operator
#: passes an explicit list (``rules=()`` disables alerting). Metric
#: selectors are audited against the DECLARED_* registries by batonlint
#: BTL033 — a typo here would otherwise mean "the alert never fires".
DEFAULT_RULES = [
    {
        "name": "straggler_rate",
        "metric": "rounds.straggler_rate",
        "op": ">",
        "threshold": 0.25,
        "for_s": 0.0,
        "cooldown_s": 60.0,
        "severity": "page",
        "capture": True,
        "description": "more than a quarter of recent participants "
                       "straggled past the reporting window",
    },
    {
        "name": "round_duration_p95_regression",
        "metric": "rounds.duration_p95_ratio",
        "op": ">",
        "threshold": 2.0,
        "for_s": 5.0,
        "cooldown_s": 120.0,
        "severity": "warn",
        "description": "recent rounds' p95 duration doubled vs the "
                       "older half of the tail window",
    },
    {
        "name": "recompile_storm",
        "metric": "rounds.recompile_storm_rounds",
        "op": ">=",
        "threshold": 1.0,
        "for_s": 0.0,
        "cooldown_s": 120.0,
        "severity": "warn",
        "capture": True,
        "description": "a recent round saw recompile storms (shape "
                       "churn recompiling XLA every call)",
    },
    {
        "name": "degrading_mfu",
        "metric": "rounds.mfu_ratio",
        "op": "<",
        "threshold": 0.67,
        "for_s": 5.0,
        "cooldown_s": 120.0,
        "severity": "warn",
        "description": "fleet MFU over recent rounds fell by a third "
                       "vs the older half of the tail window",
    },
    {
        "name": "loop_lag",
        "metric": "timer:loop_lag_s:p95",
        "op": ">",
        "threshold": 0.5,
        "for_s": 2.0,
        "cooldown_s": 60.0,
        "severity": "page",
        "capture": True,
        "description": "event-loop scheduling delay p95 above 500ms — "
                       "something synchronous is hogging the loop",
    },
]


# ---------------------------------------------------------------------------
# Metric view: the flat namespace one evaluation tick sees


def derive_rounds_tail(
    records: Sequence[dict], window: int = 8
) -> Dict[str, float]:
    """``rounds.*`` series from the last ``window`` round records
    (oldest first). Ratio metrics split the window in half; they only
    exist once both halves have data — a rule on a ratio simply skips
    until then (absent metric => not evaluable, never a crash)."""
    tail = [r for r in records if isinstance(r, dict)][-max(1, window):]
    m: Dict[str, float] = {}
    if not tail:
        return m
    m["rounds.tail"] = float(len(tail))
    participants = sum(_count(r.get("participants")) for r in tail)
    if participants:
        m["rounds.straggler_rate"] = sum(
            _count(r.get("stragglers")) for r in tail
        ) / participants
    durs = [float(r["duration_s"]) for r in tail
            if r.get("outcome") == "completed"
            and isinstance(r.get("duration_s"), (int, float))]
    if durs:
        m["rounds.duration_p95"] = _quantile(sorted(durs), 0.95)
        if len(durs) >= 4:
            half = len(durs) // 2
            older = _quantile(sorted(durs[:half]), 0.95)
            recent = _quantile(sorted(durs[half:]), 0.95)
            if older > 0:
                m["rounds.duration_p95_ratio"] = recent / older
    m["rounds.recompile_storm_rounds"] = float(sum(
        1 for r in tail
        if isinstance(r.get("compute"), dict)
        and r["compute"].get("recompile_storms")
    ))
    mfus = [float(r["compute"]["mfu"]) for r in tail
            if isinstance(r.get("compute"), dict)
            and isinstance(r["compute"].get("mfu"), (int, float))]
    if mfus:
        m["rounds.mfu_mean"] = sum(mfus) / len(mfus)
        if len(mfus) >= 4:
            half = len(mfus) // 2
            older = sum(mfus[:half]) / half
            recent = sum(mfus[half:]) / (len(mfus) - half)
            if older > 0:
                m["rounds.mfu_ratio"] = recent / older
    return m


def build_metric_view(
    snapshot: Optional[dict],
    rounds_tail: Sequence[dict] = (),
    rounds_window: int = 8,
) -> Dict[str, float]:
    """Flatten one node's metrics snapshot + rounds tail into the flat
    ``{address: float}`` namespace rules select from."""
    m: Dict[str, float] = {}
    if snapshot:
        for k, v in (snapshot.get("counters") or {}).items():
            m[f"counter:{k}"] = float(v)
        for k, v in (snapshot.get("gauges") or {}).items():
            m[f"gauge:{k}"] = float(v)
        for name, st in (snapshot.get("timers") or {}).items():
            for stat, key in TIMER_STATS.items():
                if key in st:
                    m[f"timer:{name}:{stat}"] = float(st[key])
    m.update(derive_rounds_tail(rounds_tail, rounds_window))
    return m


def resolve_view_metric(
    view: Dict[str, float], name: str
) -> Tuple[Optional[float], Optional[str]]:
    """``(value, skip_reason)``: counters default to 0 when untouched
    (same absence-is-zero rule as the SLO evaluator); everything else
    absent means *not evaluable this tick*, with the reason recorded."""
    val = view.get(name)
    if val is not None:
        return float(val), None
    if name.startswith(_ZERO_DEFAULT_PREFIXES):
        return 0.0, None
    return None, f"metric {name!r} not present in this node's namespace"


def windowed_rate(
    history: Optional[Sequence[dict]],
    counter: str,
    window_s: float,
    now: float,
) -> Tuple[Optional[float], Optional[str]]:
    """Per-second rate of ``counter`` over the history-ring samples in
    ``[now - window_s, now]`` — ``(rate, reason)``, rate None when the
    window lacks coverage (burn-rate rules then skip, they never guess)."""
    snaps = sorted(
        (s for s in (history or [])
         if isinstance(s, dict)
         and isinstance(s.get("ts"), (int, float))
         and s["ts"] >= now - window_s),
        key=lambda s: s["ts"],
    )
    if len(snaps) < 2:
        return None, (f"history window {window_s:g}s holds "
                      f"{len(snaps)} samples (need >= 2)")
    first, last = snaps[0], snaps[-1]
    span = float(last["ts"]) - float(first["ts"])
    if span <= 0:
        return None, f"history window {window_s:g}s has zero span"
    delta = (float((last.get("counters") or {}).get(counter, 0.0))
             - float((first.get("counters") or {}).get(counter, 0.0)))
    return delta / span, None


def _count(v: Any) -> int:
    if isinstance(v, (list, tuple)):
        return len(v)
    if isinstance(v, (int, float)):
        return int(v)
    return 0


def _quantile(sorted_vals: Sequence[float], q: float) -> float:
    n = len(sorted_vals)
    if n == 1:
        return sorted_vals[0]
    rank = q * (n - 1)
    lo = int(rank)
    hi = min(lo + 1, n - 1)
    frac = rank - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def read_alerts_jsonl(path: str) -> Tuple[List[dict], int]:
    """Tolerant ``alerts.jsonl`` reader — ``(events, n_torn)``, same
    contract as :func:`baton_tpu.utils.slog.read_rounds_jsonl`."""
    from baton_tpu.utils.slog import read_rounds_jsonl

    return read_rounds_jsonl(path)


# ---------------------------------------------------------------------------
# Engine


@dataclass
class _RuleState:
    state: str = "ok"            # ok | pending | firing
    pending_since: Optional[float] = None
    firing_since: Optional[float] = None
    cooldown_until: float = 0.0
    episodes: int = 0
    last_value: Any = None
    last_event_ts: Optional[float] = None
    skip_reason: Optional[str] = None
    last_capture_ts: Optional[float] = None
    history: List[str] = field(default_factory=list)  # recent transitions


class AlertEngine:
    """Evaluates a rule pack against successive metric views.

    One engine per node; :meth:`evaluate` is called by the node's
    ``PeriodicTask`` tick with a freshly built view and (for burn-rate
    rules) the metrics-history ring. Thread-safe on the JSONL appender;
    the state machine itself runs on the owning loop only.
    """

    def __init__(
        self,
        rules: Optional[Iterable] = None,
        *,
        log_path: Optional[str] = None,
        metrics=None,
        node: str = "manager",
        rounds_window: int = 8,
        on_capture: Optional[Callable[[AlertRule, dict], Any]] = None,
        now: Callable[[], float] = time.time,
    ) -> None:
        parsed: List[AlertRule] = []
        for i, r in enumerate(DEFAULT_RULES if rules is None else rules):
            rule = r if isinstance(r, AlertRule) else AlertRule.parse(
                r, ctx=f"alert rule [{i}]"
            )
            parsed.append(rule)
        names = [r.name for r in parsed]
        dupes = sorted({n for n in names if names.count(n) > 1})
        if dupes:
            raise AlertRuleError(f"duplicate alert rule names: {dupes}")
        self.rules = parsed
        self.node = node
        self.metrics = metrics
        self.rounds_window = max(1, int(rounds_window))
        self.on_capture = on_capture
        self._now = now
        self._log_path = log_path
        self._log_lock = threading.Lock()
        if log_path:
            os.makedirs(
                os.path.dirname(os.path.abspath(log_path)), exist_ok=True
            )
        self._states: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules
        }

    # -- persistence ---------------------------------------------------
    def _append(self, record: dict) -> None:
        if not self._log_path:
            return
        # crash-safety: one write() + flush per line, same discipline as
        # RoundsLog — a crash tears at most the final line
        data = json.dumps(record, default=repr) + "\n"
        with self._log_lock:
            with open(self._log_path, "a", encoding="utf-8") as fh:
                fh.write(data)
                fh.flush()

    def _inc(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name)

    def log_event(self, record: dict) -> None:
        """Append an out-of-band event (e.g. a built forensics bundle)
        to ``alerts.jsonl`` with the lifecycle events, so one file tells
        the whole story of an incident."""
        self._append(dict(record, node=self.node))

    def _emit(self, event: str, rule: AlertRule, st: _RuleState,
              now: float, **extra) -> dict:
        rec = {
            "ts": round(now, 6),
            "node": self.node,
            "event": event,
            "rule": rule.name,
            "severity": rule.severity,
            "metric": rule.metric,
            "value": st.last_value,
            "threshold": rule._effective_threshold(),
            "for_s": rule.for_s,
            "episode": st.episodes,
        }
        if rule.capture:
            rec["capture"] = True
        rec.update(extra)
        st.last_event_ts = now
        st.history = (st.history + [event])[-8:]
        self._append(rec)
        return rec

    # -- resolution ----------------------------------------------------
    def _resolve_rule(
        self,
        rule: AlertRule,
        view: Dict[str, float],
        history: Optional[Sequence[dict]],
        now: float,
    ) -> Tuple[Any, Optional[str]]:
        if rule.burn_rate is None:
            return resolve_view_metric(view, rule.metric)
        counter = rule.metric[len("counter:"):]
        short, why_s = windowed_rate(
            history, counter, rule.burn_rate["short_s"], now
        )
        long_, why_l = windowed_rate(
            history, counter, rule.burn_rate["long_s"], now
        )
        if short is None or long_ is None:
            return None, why_s or why_l
        return {"short": round(short, 6), "long": round(long_, 6)}, None

    # -- the tick ------------------------------------------------------
    def evaluate(
        self,
        view: Dict[str, float],
        history: Optional[Sequence[dict]] = None,
    ) -> List[dict]:
        """Step every rule's state machine against one metric view.
        Returns the emitted transition events. Never raises on a bad
        rule/metric — per-rule failures are counted and recorded."""
        now = self._now()
        events: List[dict] = []
        for rule in self.rules:
            st = self._states[rule.name]
            try:
                value, skip = self._resolve_rule(rule, view, history, now)
            except Exception as exc:
                value, skip = None, f"evaluation error: {exc!r}"
                self._inc("alerts_eval_errors")
            if value is None:
                st.skip_reason = skip
                continue  # not evaluable: hold state, try next tick
            st.skip_reason = None
            st.last_value = value
            try:
                events.extend(self._step(rule, st, value, now))
            except Exception:
                self._inc("alerts_eval_errors")
        if self.metrics is not None:
            states = [s.state for s in self._states.values()]
            self.metrics.set_gauge(
                "alerts_firing", states.count("firing")
            )
            self.metrics.set_gauge(
                "alerts_pending", states.count("pending")
            )
        return events

    def _step(self, rule: AlertRule, st: _RuleState, value: Any,
              now: float) -> List[dict]:
        out: List[dict] = []
        breach = rule.breaches(value)
        if st.state == "ok":
            if breach and now >= st.cooldown_until:
                st.state = "pending"
                st.pending_since = now
                out.append(self._emit("pending", rule, st, now))
                if rule.for_s <= 0:
                    out.append(self._fire(rule, st, now))
        elif st.state == "pending":
            if not breach:
                # transient spike: the for_s hold did its job — back to
                # ok with no firing episode and no resolved event
                st.state = "ok"
                st.pending_since = None
            elif now - st.pending_since >= rule.for_s:
                out.append(self._fire(rule, st, now))
        elif st.state == "firing":
            if not rule.still_breaching(value):
                st.state = "ok"
                st.firing_since = None
                st.pending_since = None
                st.cooldown_until = now + rule.cooldown_s
                self._inc("alerts_resolved_total")
                out.append(self._emit(
                    "resolved", rule, st, now,
                    cooldown_until=round(st.cooldown_until, 6),
                ))
        return out

    def _fire(self, rule: AlertRule, st: _RuleState, now: float) -> dict:
        st.state = "firing"
        st.firing_since = now
        st.episodes += 1
        self._inc("alerts_fired_total")
        extra: Dict[str, Any] = {}
        if rule.capture and self.on_capture is not None:
            if (st.last_capture_ts is None
                    or now - st.last_capture_ts >= rule.cooldown_s):
                st.last_capture_ts = now
                self._inc("alerts_captures_armed")
                extra["capture_armed"] = True
            else:
                extra["capture_armed"] = False
                extra["capture_suppressed"] = (
                    f"per-rule capture cooldown ({rule.cooldown_s:g}s)"
                )
        event = self._emit("firing", rule, st, now, **extra)
        if extra.get("capture_armed"):
            try:
                self.on_capture(rule, event)
            except Exception:
                # capture arming is advisory; a broken hook must not
                # take the alert lifecycle down with it
                self._inc("alerts_eval_errors")
        return event

    # -- introspection -------------------------------------------------
    def status_snapshot(self) -> dict:
        """The ``GET /{name}/alerts`` payload."""
        now = self._now()
        rules = []
        for rule in self.rules:
            st = self._states[rule.name]
            rules.append({
                "name": rule.name,
                "metric": rule.metric,
                "op": rule.op,
                "threshold": rule._effective_threshold(),
                "burn_rate": rule.burn_rate,
                "for_s": rule.for_s,
                "cooldown_s": rule.cooldown_s,
                "severity": rule.severity,
                "capture": rule.capture,
                "description": rule.description,
                "state": st.state,
                "value": st.last_value,
                "episodes": st.episodes,
                "pending_since": st.pending_since,
                "firing_since": st.firing_since,
                "cooldown_until": st.cooldown_until or None,
                "skip_reason": st.skip_reason,
                "recent_transitions": list(st.history),
            })
        firing = [r["name"] for r in rules if r["state"] == "firing"]
        pending = [r["name"] for r in rules if r["state"] == "pending"]
        return {
            "node": self.node,
            "ts": round(now, 6),
            "rules": rules,
            "firing": firing,
            "pending": pending,
            "summary": {
                "rules": len(rules),
                "firing": len(firing),
                "pending": len(pending),
                "page_firing": sum(
                    1 for r in rules
                    if r["state"] == "firing" and r["severity"] == "page"
                ),
            },
        }

    def firing(self, severity: Optional[str] = None) -> List[str]:
        """Names of currently-firing rules, optionally filtered."""
        out = []
        for rule in self.rules:
            if self._states[rule.name].state != "firing":
                continue
            if severity is not None and rule.severity != severity:
                continue
            out.append(rule.name)
        return out
