"""Anomaly-triggered forensics bundles: capture the deep evidence *in
the moment*, because after the fact it is gone.

When a ``capture: true`` alert fires (:mod:`baton_tpu.obs.alerts`),
the manager arms a capture for the **next round** and, when that round
finishes, packages one **bundle** — a content-addressed manifest of
evidence sections:

``jax_profile``
    a programmatic ``jax.profiler`` trace of the training step that ran
    while armed (armed via :func:`baton_tpu.utils.profiling
    .arm_forensics_trace`, consumed by the worker's local-train call
    site; graceful no-op off-TPU and in processes where no step ran);
``task_stacks``
    an asyncio all-tasks stack dump of the capturing process — the
    "what was the loop doing" evidence for loop-lag pages;
``loop_lag``
    the loop-lag histogram snapshot (p50/p95/p99 + buckets);
``fleet_slice``
    the fleet-ledger classification slice for the implicated clients
    (the round's stragglers, or every non-healthy client);
``round_trace``
    the round's Chrome-trace export (every span across tiers);
``metric_history``
    the metrics-history window around the capture.

**Null-with-reason invariant** (same rule as :mod:`baton_tpu.obs
.compute`): a section that could not be captured is ``null`` with a
sibling ``<section>_reason`` string — a silent hole in a forensics
bundle would read as "nothing happened" exactly when something did.
:func:`build_manifest` enforces it by construction and
:func:`validate_manifest` re-checks any manifest.

Bundles are **content-addressed**: the digest is the SHA-256 of the
canonical manifest JSON, served at ``GET /{name}/forensics/{digest}``.
The store keeps a bounded ring (oldest evicted); trace ids referenced
by retained bundles are exempted from the trace-spool GC
(:func:`baton_tpu.utils.tracing.gc_spool`).

Pure stdlib; jax is only touched by the profiling wrappers this module
deliberately does not import.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import threading
import traceback
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Set

from baton_tpu.obs.compute import validate_record

__all__ = [
    "EVIDENCE_SECTIONS",
    "ForensicsStore",
    "build_manifest",
    "validate_manifest",
    "dump_asyncio_tasks",
    "profile_dir_summary",
]

#: every evidence section a bundle carries (present or null-with-reason)
EVIDENCE_SECTIONS = (
    "jax_profile",
    "task_stacks",
    "loop_lag",
    "fleet_slice",
    "round_trace",
    "metric_history",
)

_DEFAULT_REASONS = {
    "jax_profile": "no training step ran through the armed profiler",
    "task_stacks": "no running event loop to dump",
    "loop_lag": "loop-lag histogram not recorded on this node",
    "fleet_slice": "no fleet ledger on this node",
    "round_trace": "no trace recorded for the captured round",
    "metric_history": "metrics history ring empty",
}


def dump_asyncio_tasks(limit: int = 200) -> List[dict]:
    """Stack dump of every task on the current event loop — the
    "what was the process doing when the alert fired" evidence. Must be
    called from loop context; raises RuntimeError outside one (callers
    turn that into a ``*_reason``)."""
    out: List[dict] = []
    current = asyncio.current_task()
    for task in list(asyncio.all_tasks())[:limit]:
        frames = []
        for fr in task.get_stack(limit=12):
            frames.append(
                f"{fr.f_code.co_filename}:{fr.f_lineno} "
                f"{fr.f_code.co_name}"
            )
        coro = task.get_coro()
        out.append({
            "name": task.get_name(),
            "coro": getattr(coro, "__qualname__", repr(coro)),
            "current": task is current,
            "done": task.done(),
            "stack": frames,
        })
    return out


def profile_dir_summary(log_dir: Optional[str]) -> Optional[dict]:
    """What an armed ``jax.profiler`` capture actually produced: the
    directory plus every file (relative path + bytes). None when the
    directory is absent or empty — callers record the reason."""
    if not log_dir or not os.path.isdir(log_dir):
        return None
    files = []
    total = 0
    for root, _dirs, names in os.walk(log_dir):
        for name in names:
            full = os.path.join(root, name)
            try:
                size = os.path.getsize(full)
            except OSError:
                size = 0
            files.append({
                "path": os.path.relpath(full, log_dir),
                "bytes": size,
            })
            total += size
    if not files:
        return None
    files.sort(key=lambda f: f["path"])
    return {"log_dir": log_dir, "files": files, "total_bytes": total}


def validate_manifest(manifest: dict) -> List[str]:
    """Violations of the bundle contract (empty list = valid): every
    declared evidence section present in ``sections``, and every null
    section excused by a ``<name>_reason`` sibling."""
    bad: List[str] = []
    sections = manifest.get("sections")
    if not isinstance(sections, dict):
        return ["manifest has no `sections` object"]
    for name in EVIDENCE_SECTIONS:
        if name not in sections:
            bad.append(f"evidence section {name!r} missing entirely")
    bad.extend(validate_record(sections))
    return bad


def build_manifest(
    *,
    rule: str,
    severity: str = "warn",
    round_name: Optional[str] = None,
    trace_id: Optional[str] = None,
    node: str = "manager",
    armed_ts: Optional[float] = None,
    captured_ts: Optional[float] = None,
    sections: Optional[Dict[str, Any]] = None,
    reasons: Optional[Dict[str, str]] = None,
) -> dict:
    """Assemble one bundle manifest. ``sections`` holds whatever
    evidence WAS captured; anything absent or None becomes
    null-with-reason (caller-supplied ``reasons`` first, then the
    section's stock reason). Raises if the result would break the
    invariant — unreachable via this builder, kept as a guard."""
    sections = sections or {}
    reasons = reasons or {}
    body: Dict[str, Any] = {}
    for name in EVIDENCE_SECTIONS:
        val = sections.get(name)
        if val is not None:
            body[name] = val
        else:
            body[name] = None
            body[f"{name}_reason"] = (
                reasons.get(name) or _DEFAULT_REASONS[name]
            )
    manifest = {
        "rule": rule,
        "severity": severity,
        "round": round_name,
        "trace_id": trace_id,
        "node": node,
        "armed_ts": armed_ts,
        "captured_ts": captured_ts,
        "sections_present": sum(
            1 for name in EVIDENCE_SECTIONS if body[name] is not None
        ),
        "sections": body,
    }
    if round_name is None:
        manifest["round_reason"] = reasons.get(
            "round", "captured outside a finished round"
        )
    if trace_id is None:
        manifest["trace_id_reason"] = reasons.get(
            "trace_id", "no trace id for the captured round"
        )
    if armed_ts is None:
        manifest["armed_ts_reason"] = "capture was not pre-armed"
    if captured_ts is None:
        manifest["captured_ts_reason"] = "capture time unrecorded"
    violations = validate_manifest(manifest)
    if violations:  # by-construction guard
        raise ValueError(
            f"forensics manifest breaks null-with-reason: {violations}"
        )
    return manifest


class ForensicsStore:
    """Bounded, content-addressed bundle store.

    ``put`` digests the canonical manifest JSON (sha256, 32 hex chars —
    same shape as trace ids) and retains the newest ``max_bundles``;
    with a ``dir_path`` each manifest is also persisted as
    ``<digest>.json`` (one write + atomic rename) so bundles survive a
    process restart and ride CI artifact uploads. Thread-safe."""

    def __init__(
        self,
        dir_path: Optional[str] = None,
        max_bundles: int = 16,
    ) -> None:
        self.dir_path = dir_path
        self.max_bundles = max(1, int(max_bundles))
        self._bundles: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()
        if dir_path:
            os.makedirs(dir_path, exist_ok=True)

    @staticmethod
    def digest_of(manifest: dict) -> str:
        blob = json.dumps(
            {k: v for k, v in manifest.items() if k != "digest"},
            sort_keys=True, default=repr,
        ).encode("utf-8")
        return hashlib.sha256(blob).hexdigest()[:32]

    def put(self, manifest: dict) -> str:
        violations = validate_manifest(manifest)
        if violations:
            raise ValueError(
                f"refusing to store invalid forensics bundle: {violations}"
            )
        digest = self.digest_of(manifest)
        stored = dict(manifest, digest=digest)
        evicted: List[str] = []
        with self._lock:
            self._bundles[digest] = stored
            self._bundles.move_to_end(digest)
            while len(self._bundles) > self.max_bundles:
                old, _ = self._bundles.popitem(last=False)
                evicted.append(old)
        if self.dir_path:
            path = os.path.join(self.dir_path, f"{digest}.json")
            tmp = f"{path}.tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(stored, fh, indent=2, default=repr)
                fh.write("\n")
            os.replace(tmp, path)
            for old in evicted:
                try:
                    os.remove(os.path.join(self.dir_path, f"{old}.json"))
                except OSError:
                    pass
        return digest

    def get(self, digest: str) -> Optional[dict]:
        with self._lock:
            found = self._bundles.get(digest)
            if found is not None:
                return found
        if self.dir_path:
            path = os.path.join(self.dir_path, f"{digest}.json")
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    return json.load(fh)
            except (OSError, ValueError):
                return None
        return None

    def list_bundles(self) -> List[dict]:
        """Newest-first index (digest + headline fields, no sections)."""
        with self._lock:
            items = list(self._bundles.values())
        return [
            {
                "digest": b.get("digest"),
                "rule": b.get("rule"),
                "severity": b.get("severity"),
                "round": b.get("round"),
                "trace_id": b.get("trace_id"),
                "captured_ts": b.get("captured_ts"),
                "sections_present": b.get("sections_present"),
            }
            for b in reversed(items)
        ]

    def referenced_trace_ids(self) -> Set[str]:
        """Trace ids any retained bundle still points at — the spool-GC
        exemption set (a GC'd round trace would hollow out the bundle's
        ``round_trace`` evidence)."""
        with self._lock:
            return {
                b["trace_id"] for b in self._bundles.values()
                if b.get("trace_id")
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._bundles)


def safe_repr_exc(exc: BaseException) -> str:
    """One-line capture-failure description for ``*_reason`` fields."""
    line = traceback.format_exception_only(type(exc), exc)
    return (line[-1].strip() if line else repr(exc))[:200]
