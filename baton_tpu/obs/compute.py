"""Compute-plane probe: analytic FLOPs/MFU accounting, jit compile
tracking, and peak-HBM reading for the LIVE training path.

Until this module existed, MFU / compile seconds / peak HBM were
measured only inside offline ``bench.py`` runs — the round loop itself
was blind on the compute plane. The probe instruments every local
training call (worker ``_run_round``, the manager's simulated cohort via
``parallel/engine.py``) and emits one *compute record* per round, which
rides the update metadata to the root, lands in the round's
``rounds.jsonl`` SLO record (``compute`` section), feeds the per-client
fleet ledger, and gates ``compute:*`` SLO metrics in CI.

Three design rules, each a recorded postmortem:

* **One FLOPs implementation.** The per-model analytic FLOPs constants
  and the MFU formula live HERE; ``bench.py`` imports them. Bench MFU
  and live MFU can no longer diverge (they were duplicated before).
* **Null-with-reason.** Every ``None`` metric in a compute record
  carries a sibling ``<name>_reason`` / ``<name>_source`` string
  (:func:`validate_record` enforces it). The BENCH_r04 lesson: a silent
  null reads as "stopped measuring" and hides regressions.
* **Compile visibility.** :class:`CompileTracker` watches the shape
  signatures each jitted callable is invoked with: a new signature is a
  cache miss (XLA compiled during that call), and repeated new
  signatures within a short window are a *recompile storm* — the
  shape-churn pathology that silently multiplies round latency.

``compile_s`` on a cache miss is the compiling call's wall time — an
upper bound that includes one execution (the live path cannot afford a
separate warm-up run; ``compile_s_source`` says so). On a cache hit it is
an exact 0.0.

Pure stdlib + optional lazy jax: the FLOPs/MFU math and the tracker
import and unit-test without an accelerator stack.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "RESNET18_CIFAR_FWD_FLOPS_PER_IMG",
    "TRAIN_FLOPS_PER_IMG",
    "TPU_PEAK_FLOPS",
    "MODEL_FAMILY_FLOPS",
    "register_model_flops",
    "model_family_of",
    "train_flops_per_sample",
    "peak_flops_for",
    "compute_mfu",
    "CompileTracker",
    "ComputeProbe",
    "build_record",
    "validate_record",
    "summarize_round",
    "RECOMPILE_STORM_THRESHOLD",
    "RECOMPILE_STORM_WINDOW",
]

# ---------------------------------------------------------------------------
# Analytic FLOPs accounting (extracted from bench.py — the ONE copy).
#
# ResNet-18 (CIFAR-10 variant, 32x32 input): 0.557 GMAC forward per
# image = 1.11 GFLOP (x2 MAC->FLOP); training ~3x forward (fwd + 2x
# bwd).
RESNET18_CIFAR_FWD_FLOPS_PER_IMG = 1.11e9
TRAIN_FLOPS_PER_IMG = 3.0 * RESNET18_CIFAR_FWD_FLOPS_PER_IMG

# Peak dense-matmul throughput by device kind (bf16, FLOP/s) — the MFU
# denominator. Source: public TPU spec sheets. Prefix-matched against
# ``device.device_kind`` (platform strings vary: "TPU v5 lite" on the
# axon tunnel, "TPU v5e" in docs).
TPU_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # Trillium / v6e
    "TPU v6e": 918e12,
}

#: analytic *training* FLOPs per sample, by model family
MODEL_FAMILY_FLOPS: Dict[str, float] = {
    "resnet18_cifar": TRAIN_FLOPS_PER_IMG,
}

# model-name prefix -> family key in MODEL_FAMILY_FLOPS (FedModel.name
# is free-form; bench's model is named "resnet18*")
_FAMILY_PREFIXES: List[Tuple[str, str]] = [
    ("resnet18", "resnet18_cifar"),
]


def register_model_flops(
    family: str,
    train_flops_per_sample: float,
    name_prefixes: Sequence[str] = (),
) -> None:
    """Register a model family's analytic training FLOPs per sample so
    live rounds on that family get measured MFU. ``name_prefixes`` maps
    ``FedModel.name`` values to the family."""
    if not (train_flops_per_sample > 0):
        raise ValueError("train_flops_per_sample must be > 0")
    MODEL_FAMILY_FLOPS[family] = float(train_flops_per_sample)
    for p in name_prefixes:
        _FAMILY_PREFIXES.append((str(p), family))


def model_family_of(model: Any) -> Tuple[Optional[str], Optional[str]]:
    """``(family, reason)`` for a model (a :class:`FedModel`, anything
    with a ``name``, or a bare name string). ``family`` is a key of
    :data:`MODEL_FAMILY_FLOPS`; unknown models return
    ``(None, reason)`` — an unknown family is *expected* (linear smoke
    models, custom nets) and downstream MFU is null-with-reason."""
    name = model if isinstance(model, str) else getattr(model, "name", None)
    if not name:
        return None, "model has no name attribute"
    for prefix, family in _FAMILY_PREFIXES:
        if name.startswith(prefix):
            return family, None
    return None, f"no FLOPs accounting registered for model {name!r}"


def train_flops_per_sample(
    family: Optional[str],
) -> Tuple[Optional[float], Optional[str]]:
    """Analytic training FLOPs per sample for ``family``, or
    ``(None, reason)``."""
    if family is None:
        return None, "model family unknown"
    flops = MODEL_FAMILY_FLOPS.get(family)
    if flops is None:
        return None, f"no FLOPs accounting for family {family!r}"
    return flops, None


def peak_flops_for(
    device_kind: str,
) -> Tuple[Optional[float], Optional[str]]:
    """Chip peak FLOP/s for a ``device_kind`` string (prefix-matched),
    or ``(None, reason)`` — CPU smoke runs have no meaningful peak."""
    for prefix, peak in TPU_PEAK_FLOPS.items():
        if device_kind.startswith(prefix):
            return peak, None
    return None, f"no peak-FLOPs spec for device kind {device_kind!r}"


def compute_mfu(
    samples_per_sec_per_chip: Optional[float],
    flops_per_sample: Optional[float],
    device_kind: str,
) -> Tuple[Optional[float], Optional[str]]:
    """MFU = delivered analytic training FLOPs / chip peak — the exact
    formula bench.py's headline uses. ``(None, reason)`` when any input
    is unavailable."""
    if samples_per_sec_per_chip is None:
        return None, "throughput unmeasured"
    if flops_per_sample is None:
        return None, "model FLOPs unavailable"
    peak, why = peak_flops_for(device_kind)
    if peak is None:
        return None, why
    return samples_per_sec_per_chip * flops_per_sample / peak, None


# ---------------------------------------------------------------------------
# Compile tracking

#: new shape signatures within the window that flag a recompile storm —
#: one compile per new (cohort, epochs) shape is expected; three in a
#: window of eight rounds means the shapes are churning
RECOMPILE_STORM_THRESHOLD = 3
RECOMPILE_STORM_WINDOW = 8


class CompileTracker:
    """Shape-signature watcher for jitted callables.

    The live path cannot see inside XLA's jit cache, but it controls the
    cache key: a call with a signature this tracker has not seen for
    ``key`` compiled during that call. ``observe`` returns the compile
    fields of the round's compute record.
    """

    def __init__(
        self,
        storm_window: int = RECOMPILE_STORM_WINDOW,
        storm_threshold: int = RECOMPILE_STORM_THRESHOLD,
    ) -> None:
        self.storm_window = max(2, int(storm_window))
        self.storm_threshold = max(2, int(storm_threshold))
        self._sigs: Dict[Any, set] = {}
        self._recent: Dict[Any, deque] = {}

    def observe(
        self,
        key: Any,
        signature: Any,
        wall_s: Optional[float] = None,
    ) -> dict:
        """Record one invocation of callable ``key`` with shape
        ``signature``; ``wall_s`` is that call's wall time (the
        compile_s upper bound on a miss)."""
        sigs = self._sigs.setdefault(key, set())
        miss = signature not in sigs
        if miss:
            sigs.add(signature)
        recent = self._recent.setdefault(
            key, deque(maxlen=self.storm_window)
        )
        recent.append(miss)
        out: dict = {
            "cache_hit": not miss,
            "recompiles": max(0, len(sigs) - 1),
            "recompile_storm": sum(recent) >= self.storm_threshold,
        }
        if not miss:
            out["compile_s"] = 0.0
            out["compile_s_source"] = "cache_hit"
        elif wall_s is not None:
            out["compile_s"] = float(wall_s)
            out["compile_s_source"] = "first_call_wall"
        else:
            out["compile_s"] = None
            out["compile_s_reason"] = "wall time unavailable for compiling call"
        return out


# ---------------------------------------------------------------------------
# Record building + the null-with-reason invariant

def validate_record(record: dict) -> List[str]:
    """The null-with-reason invariant: every ``None`` value must have a
    non-empty ``<key>_reason`` or ``<key>_source`` sibling string.
    Returns the violations (empty = valid)."""
    bad = []
    for key, val in record.items():
        if val is not None:
            continue
        if key.endswith(("_reason", "_source")):
            bad.append(f"{key}: reason/source field itself is null")
            continue
        excuse = record.get(f"{key}_reason") or record.get(f"{key}_source")
        if not (isinstance(excuse, str) and excuse):
            bad.append(f"{key}: null without {key}_reason/{key}_source")
    return bad


def build_record(
    *,
    train_s: float,
    n_samples: float,
    n_epochs: int = 1,
    steps: Optional[int] = None,
    device_kind: str = "unknown",
    n_chips: int = 1,
    model_family: Optional[str] = None,
    model_family_reason: Optional[str] = None,
    compile_fields: Optional[dict] = None,
    peak_hbm_gb: Optional[float] = None,
    peak_hbm_source: Optional[str] = None,
    peak_hbm_reason: Optional[str] = None,
) -> dict:
    """Assemble one round's compute record, deriving throughput and MFU
    and enforcing the null-with-reason invariant by construction."""
    train_s = float(train_s)
    n_chips = max(1, int(n_chips))
    rec: dict = {
        "train_s": round(train_s, 6),
        "steps": int(steps) if steps is not None else int(max(1, n_epochs)),
        "n_chips": n_chips,
        "device_kind": device_kind,
    }
    if model_family is not None:
        rec["model_family"] = model_family
    else:
        rec["model_family"] = None
        rec["model_family_reason"] = (
            model_family_reason or "model family unknown"
        )
    if train_s > 0 and n_samples > 0:
        sps = float(n_samples) * max(1, int(n_epochs)) / train_s
        rec["samples_per_sec"] = round(sps, 3)
        rec["samples_per_sec_per_chip"] = round(sps / n_chips, 3)
    else:
        why = "zero training wall time" if n_samples > 0 else "no samples"
        rec["samples_per_sec"] = None
        rec["samples_per_sec_reason"] = why
        rec["samples_per_sec_per_chip"] = None
        rec["samples_per_sec_per_chip_reason"] = why
    flops, flops_why = train_flops_per_sample(rec.get("model_family"))
    if flops is not None:
        rec["flops_per_sample"] = flops
    else:
        rec["flops_per_sample"] = None
        rec["flops_per_sample_reason"] = flops_why or "model FLOPs unavailable"
    mfu, mfu_why = compute_mfu(
        rec.get("samples_per_sec_per_chip"), flops, device_kind
    )
    if mfu is not None:
        rec["mfu"] = round(mfu, 6)
    else:
        rec["mfu"] = None
        rec["mfu_reason"] = mfu_why or "mfu unavailable"
    rec.update(compile_fields or {
        "compile_s": None,
        "compile_s_reason": "compile tracking not wired for this path",
    })
    if peak_hbm_gb is not None:
        rec["peak_hbm_gb"] = round(float(peak_hbm_gb), 6)
        rec["peak_hbm_gb_source"] = peak_hbm_source or "unspecified"
    else:
        rec["peak_hbm_gb"] = None
        rec["peak_hbm_gb_reason"] = (
            peak_hbm_reason or "no allocator stats or memory plan available"
        )
    violations = validate_record(rec)
    if violations:  # by-construction guard; unreachable via this builder
        raise ValueError(f"compute record breaks null-with-reason: "
                         f"{violations}")
    return rec


class ComputeProbe:
    """Per-process probe instrumenting one training call site.

    One probe per worker / engine; :meth:`record_round` is called once
    per round with that round's wall time + shape signature and returns
    the compute record (compile fields via the shared tracker, HBM via
    the runtime allocator falling back to reasons)."""

    def __init__(
        self,
        model: Any = None,
        model_family: Optional[str] = None,
        storm_window: int = RECOMPILE_STORM_WINDOW,
        storm_threshold: int = RECOMPILE_STORM_THRESHOLD,
    ) -> None:
        if model_family is not None:
            self.model_family: Optional[str] = model_family
            self.model_family_reason: Optional[str] = None
        else:
            self.model_family, self.model_family_reason = (
                model_family_of(model) if model is not None
                else (None, "no model attached to probe")
            )
        self.tracker = CompileTracker(storm_window, storm_threshold)
        # device topology is fixed for the life of the process; cache the
        # lookups so record_round stays off the jax client per round
        self._cached_device: Any = None
        self._cached_n_chips: Optional[int] = None

    @staticmethod
    def _device():
        try:
            import jax

            return jax.devices()[0]
        except Exception:
            return None

    @staticmethod
    def _peak_hbm(device) -> Tuple[Optional[float], Optional[str],
                                   Optional[str]]:
        """(gb, source, reason) — allocator stats preferred, then the
        shared :func:`baton_tpu.utils.profiling.peak_hbm_gb` plan-space
        path (a no-op without a jitted program), then a reason."""
        if device is None:
            return None, None, "no jax device available"
        try:
            from baton_tpu.utils.profiling import peak_hbm_gb

            gb, src = peak_hbm_gb(device)
        except Exception as exc:
            return None, None, f"hbm probe failed: {type(exc).__name__}"
        if gb is not None:
            return gb, src, None
        plat = getattr(device, "platform", "unknown")
        return None, None, (
            f"runtime surfaces no allocator stats on platform {plat!r}"
        )

    def record_round(
        self,
        *,
        key: Any,
        signature: Any,
        train_s: float,
        n_samples: float,
        n_epochs: int = 1,
        steps: Optional[int] = None,
        device: Any = None,
        n_chips: Optional[int] = None,
    ) -> dict:
        if device is not None:
            dev = device
        else:
            if self._cached_device is None:
                self._cached_device = self._device()
            dev = self._cached_device
        device_kind = getattr(
            dev, "device_kind", getattr(dev, "platform", "unknown")
        ) if dev is not None else "unknown"
        if n_chips is None:
            if self._cached_n_chips is None:
                try:
                    import jax

                    self._cached_n_chips = jax.device_count()
                except Exception:
                    self._cached_n_chips = 1
            n_chips = self._cached_n_chips
        compile_fields = self.tracker.observe(key, signature, wall_s=train_s)
        hbm_gb, hbm_src, hbm_why = self._peak_hbm(dev)
        return build_record(
            train_s=train_s,
            n_samples=n_samples,
            n_epochs=n_epochs,
            steps=steps,
            device_kind=str(device_kind),
            n_chips=int(n_chips),
            model_family=self.model_family,
            model_family_reason=self.model_family_reason,
            compile_fields=compile_fields,
            peak_hbm_gb=hbm_gb,
            peak_hbm_source=hbm_src,
            peak_hbm_reason=hbm_why,
        )


# ---------------------------------------------------------------------------
# Round-level aggregation (the rounds.jsonl ``compute`` section)

def _nums(records: Sequence[dict], key: str) -> List[float]:
    return [
        float(r[key]) for r in records
        if isinstance(r.get(key), (int, float))
        and not isinstance(r.get(key), bool)
        and math.isfinite(float(r[key]))
    ]


def _first_reason(records: Sequence[dict], key: str, default: str) -> str:
    for r in records:
        why = r.get(f"{key}_reason") or r.get(f"{key}_source")
        if isinstance(why, str) and why:
            return why
    return default


def summarize_round(records: Sequence[dict]) -> dict:
    """Fold the reporters' per-client compute records into one round
    ``compute`` section. Aggregates keep the null-with-reason rule: a
    value no reporter measured is null with the first reporter's reason
    (or an explicit "no compute records")."""
    records = [r for r in records if isinstance(r, dict)]
    out: dict = {"reporters": len(records)}
    if not records:
        for key in ("compile_s", "steps", "samples_per_sec_per_chip",
                    "mfu", "peak_hbm_gb"):
            out[key] = None
            out[f"{key}_reason"] = "no compute records this round"
        out["recompile_storms"] = 0
        return out

    def put(key: str, vals: List[float], agg) -> None:
        if vals:
            out[key] = round(agg(vals), 6)
        else:
            out[key] = None
            out[f"{key}_reason"] = _first_reason(
                records, key, f"no reporter measured {key}"
            )

    put("compile_s", _nums(records, "compile_s"), max)
    steps = _nums(records, "steps")
    out["steps"] = int(sum(steps)) if steps else None
    if not steps:
        out["steps_reason"] = "no reporter measured steps"
    put("samples_per_sec_per_chip",
        _nums(records, "samples_per_sec_per_chip"),
        lambda v: sum(v) / len(v))
    put("mfu", _nums(records, "mfu"), lambda v: sum(v) / len(v))
    hbm = _nums(records, "peak_hbm_gb")
    if hbm:
        out["peak_hbm_gb"] = round(max(hbm), 6)
        out["peak_hbm_gb_source"] = _first_reason(
            records, "peak_hbm_gb", "allocator"
        )
    else:
        out["peak_hbm_gb"] = None
        out["peak_hbm_gb_reason"] = _first_reason(
            records, "peak_hbm_gb", "no reporter measured peak HBM"
        )
    out["recompile_storms"] = sum(
        1 for r in records if r.get("recompile_storm")
    )
    return out
