"""Runbook plane: declarative remediations closing the observe→actuate loop.

Everything below the alerting plane is *advisory* — the ledger
classifies clients, the alert engine pages, and a human (or nobody)
reacts. This module is the reacting half: a :class:`RunbookEngine`
binds alert firings and fleet classifications to concrete, bounded
remediations the manager applies on its own invite path:

``bias_cohort``
    straggler-aware cohort selection — weighted sampling that biases
    round invites *away* from ``slow``/``flaky`` clients without ever
    hard-evicting them (their weight is reduced, never zeroed);
``overprovision``
    deadline-based over-provisioning — invite ``C·(1+ε)`` clients with
    ``ε`` derived from the recent miss (straggler) rate, so the round
    still fills its quorum when the expected fraction misses;
``adaptive_deadline``
    per-round reporting deadline fit from the fleet's observed
    ``train_s`` history (quantile × margin, clamped) instead of the
    static ``round_timeout``;
``fedbuff_fallback``
    asynchronous degradation — when churn classifications cross the
    trigger, finish a round as soon as a FedBuff-style buffer of
    ``ceil(buffer_frac · cohort)`` reports has landed rather than
    waiting out the stragglers (Nguyen et al., the same K-of-N buffer
    semantics as :mod:`baton_tpu.parallel.fedbuff`);
``pin_shapes``
    recompile-storm response — ask workers to pin batch shapes via the
    round envelope and quarantine the storm-offending clients from the
    next cohorts while the storm lasts.

Rules are **data** (parsed and validated exactly like
:class:`~baton_tpu.obs.alerts.AlertRule` — unknown keys fail at load,
the BTL034 lint class), every actuation is **explainable** (the manager
stamps each applied action into the round's ``rounds.jsonl`` record
with the triggering alert/classification and the engine appends
``entered``/``exited`` transitions to ``runbooks.jsonl``), and every
action is **reversible**: a rule holds while its trigger breaches and
exits through the same ``clear_ratio`` hysteresis the alert engine
uses — an ``{"alert": ...}`` trigger literally rides the alert's own
firing/resolved lifecycle, a metric trigger reuses
:meth:`AlertRule.breaches` / :meth:`AlertRule.still_breaching`.

Like the ledger and the alert engine this is an advisory plane: the
manager wraps every actuation site in try/except, and a runbook bug
degrades to "no remediation", never to a broken round.

Pure stdlib; imports nothing from ``server/`` so it unit-tests without
a federation.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from dataclasses import dataclass, field
from typing import (
    Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple,
)

from baton_tpu.obs.alerts import (
    ALERT_OPS,
    AlertRule,
    AlertRuleError,
    _quantile,
    resolve_view_metric,
)

__all__ = [
    "RunbookRule",
    "RunbookRuleError",
    "RunbookEngine",
    "RUNBOOK_ACTIONS",
    "ACTION_PARAMS",
    "DEFAULT_RUNBOOKS",
    "derive_fleet_view",
    "fit_deadline",
    "overprovision_count",
    "weighted_sample",
    "read_runbooks_jsonl",
]

#: the action catalog — every rule actuates exactly one of these
RUNBOOK_ACTIONS = (
    "bias_cohort",
    "overprovision",
    "adaptive_deadline",
    "fedbuff_fallback",
    "pin_shapes",
)

#: per-action parameter schema with defaults; a rule's ``params`` may
#: only override keys listed here (unknown param => parse error, the
#: same strictness as AlertRule and the BTL034 audit surface)
ACTION_PARAMS: Dict[str, Dict[str, Any]] = {
    # invite weight multiplier applied to clients whose ledger status is
    # in `statuses` — 0 < weight <= 1; never 0, biased clients must
    # still be sampled sometimes (no starvation)
    "bias_cohort": {"weight": 0.25, "statuses": ("slow", "flaky")},
    # ε = min(epsilon_max, gain · trigger_value); trigger_value is the
    # rule's own metric (typically rounds.straggler_rate = recent miss
    # rate), so provisioning tracks how much of the cohort misses
    "overprovision": {"epsilon_max": 0.5, "gain": 1.0},
    # deadline = clamp(quantile(train_s medians) · margin, min_s, max_s)
    "adaptive_deadline": {
        "quantile": 0.95, "margin": 1.5, "min_s": 0.25, "max_s": None,
    },
    # finish as soon as ceil(buffer_frac · cohort) reports have landed
    "fedbuff_fallback": {"buffer_frac": 0.5},
    # pin shapes in the round envelope; optionally quarantine the
    # clients whose observations carried recompile_storm flags
    "pin_shapes": {"quarantine": True},
}

#: statuses a bias_cohort rule may target (ledger classes, minus
#: ``inactive`` — inactive clients are already culled from sampling)
_BIASABLE_STATUSES = ("healthy", "slow", "flaky", "degrading")

#: weight below which a bias would effectively evict — refused at parse
_MIN_BIAS_WEIGHT = 0.01


class RunbookRuleError(ValueError):
    """A runbook rule failed validation — raised at parse time so a
    typo'd runbook pack fails the process start, not silently as a
    remediation that never actuates."""


@dataclass
class RunbookRule:
    """One declarative remediation. Build via :meth:`parse` (strict:
    unknown rule keys AND unknown per-action params are errors)."""

    name: str
    action: str
    trigger: dict
    for_s: float = 0.0
    cooldown_s: float = 30.0
    params: Dict[str, Any] = field(default_factory=dict)
    description: str = ""
    #: internal AlertRule evaluating a metric trigger (None for
    #: ``{"alert": ...}`` triggers, which ride the alert lifecycle)
    _trig: Optional[AlertRule] = None

    _KEYS = ("name", "action", "trigger", "for_s", "cooldown_s",
             "params", "description")
    _TRIGGER_METRIC_KEYS = ("metric", "op", "threshold", "clear_ratio")

    @staticmethod
    def parse(d: dict, ctx: str = "runbook rule") -> "RunbookRule":
        if not isinstance(d, dict):
            raise RunbookRuleError(f"{ctx}: rule must be an object, got "
                                   f"{type(d).__name__}")
        unknown = sorted(set(d) - set(RunbookRule._KEYS))
        if unknown:
            raise RunbookRuleError(f"{ctx}: unknown keys {unknown} "
                                   f"(known: {list(RunbookRule._KEYS)})")
        name = d.get("name")
        if not (isinstance(name, str) and name):
            raise RunbookRuleError(f"{ctx}: `name` must be a non-empty "
                                   f"string")
        action = d.get("action")
        if action not in RUNBOOK_ACTIONS:
            raise RunbookRuleError(f"{ctx} {name!r}: action {action!r} "
                                   f"not in {RUNBOOK_ACTIONS}")
        trigger = d.get("trigger")
        if not isinstance(trigger, dict) or not trigger:
            raise RunbookRuleError(f"{ctx} {name!r}: `trigger` must be a "
                                   f"non-empty object")
        trig_rule: Optional[AlertRule] = None
        if "alert" in trigger:
            extra = sorted(set(trigger) - {"alert"})
            if extra:
                raise RunbookRuleError(
                    f"{ctx} {name!r}: an alert trigger takes only the "
                    f"`alert` key (unknown {extra})")
            if not (isinstance(trigger["alert"], str) and trigger["alert"]):
                raise RunbookRuleError(f"{ctx} {name!r}: trigger `alert` "
                                       f"must be a non-empty string")
        else:
            extra = sorted(
                set(trigger) - set(RunbookRule._TRIGGER_METRIC_KEYS)
            )
            if extra:
                raise RunbookRuleError(
                    f"{ctx} {name!r}: unknown trigger keys {extra} (a "
                    f"trigger is {{'alert': name}} or "
                    f"{list(RunbookRule._TRIGGER_METRIC_KEYS)})")
            # delegate the full metric/op/threshold/clear_ratio
            # validation AND the hysteresis machinery to AlertRule
            try:
                trig_rule = AlertRule.parse(
                    {
                        "name": f"{name}.trigger",
                        "metric": trigger.get("metric"),
                        "op": trigger.get("op", ">"),
                        "threshold": trigger.get("threshold"),
                        "clear_ratio": trigger.get("clear_ratio"),
                    },
                    ctx=f"{ctx} {name!r} trigger",
                )
            except AlertRuleError as exc:
                raise RunbookRuleError(str(exc)) from None
        params = d.get("params", {})
        if not isinstance(params, dict):
            raise RunbookRuleError(f"{ctx} {name!r}: `params` must be an "
                                   f"object")
        schema = ACTION_PARAMS[action]
        bad = sorted(set(params) - set(schema))
        if bad:
            raise RunbookRuleError(
                f"{ctx} {name!r}: unknown params {bad} for action "
                f"{action!r} (known: {sorted(schema)})")
        merged = dict(schema)
        merged.update(params)
        RunbookRule._validate_params(name, action, merged, ctx)
        return RunbookRule(
            name=name,
            action=action,
            trigger=dict(trigger),
            for_s=max(0.0, float(d.get("for_s", 0.0))),
            cooldown_s=max(0.0, float(d.get("cooldown_s", 30.0))),
            params=merged,
            description=str(d.get("description", "")),
            _trig=trig_rule,
        )

    @staticmethod
    def _validate_params(name, action, p, ctx) -> None:
        def _num(key, lo=None, hi=None, optional=False):
            v = p.get(key)
            if v is None and optional:
                return
            try:
                v = float(v)
            except (TypeError, ValueError):
                raise RunbookRuleError(
                    f"{ctx} {name!r}: param {key!r} must be a number"
                ) from None
            if (lo is not None and v < lo) or (hi is not None and v > hi):
                raise RunbookRuleError(
                    f"{ctx} {name!r}: param {key!r}={v:g} out of range "
                    f"[{lo}, {hi}]")
            p[key] = v

        if action == "bias_cohort":
            _num("weight", _MIN_BIAS_WEIGHT, 1.0)
            statuses = p.get("statuses")
            if (not isinstance(statuses, (list, tuple)) or not statuses
                    or any(s not in _BIASABLE_STATUSES for s in statuses)):
                raise RunbookRuleError(
                    f"{ctx} {name!r}: param 'statuses' must be a "
                    f"non-empty subset of {_BIASABLE_STATUSES}")
            p["statuses"] = tuple(statuses)
        elif action == "overprovision":
            _num("epsilon_max", 0.0, 4.0)
            _num("gain", 0.0)
        elif action == "adaptive_deadline":
            _num("quantile", 0.0, 1.0)
            _num("margin", 1.0)
            _num("min_s", 0.0, optional=True)
            _num("max_s", 0.0, optional=True)
        elif action == "fedbuff_fallback":
            _num("buffer_frac", 0.0, 1.0)
            if p["buffer_frac"] <= 0.0:
                raise RunbookRuleError(
                    f"{ctx} {name!r}: buffer_frac must be > 0")
        elif action == "pin_shapes":
            p["quarantine"] = bool(p.get("quarantine", True))

    def trigger_desc(self) -> str:
        """One-line trigger description for explainability records."""
        if "alert" in self.trigger:
            return f"alert:{self.trigger['alert']}"
        t = self._trig
        return f"{t.metric} {t.op} {t._effective_threshold():g}"


#: a reasonable default pack — bias away from stragglers while the
#: straggler_rate alert fires, over-provision on sustained miss rate,
#: fall back to FedBuff buffering under churn, pin shapes on storms.
#: Operators opt in (runbooks default OFF, unlike alerts) by passing
#: ``runbook_rules="default"`` or an explicit list.
DEFAULT_RUNBOOKS = [
    {
        "name": "bias_stragglers",
        "action": "bias_cohort",
        "trigger": {"alert": "straggler_rate"},
        "params": {"weight": 0.25, "statuses": ["slow", "flaky"]},
        "description": "while the straggler_rate alert fires, invite "
                       "slow/flaky clients at quarter weight",
    },
    {
        "name": "overprovision_on_misses",
        "action": "overprovision",
        "trigger": {"metric": "rounds.straggler_rate", "op": ">",
                    "threshold": 0.15},
        "params": {"epsilon_max": 0.5, "gain": 1.5},
        "description": "invite C*(1+eps) with eps tracking the recent "
                       "miss rate",
    },
    {
        "name": "adaptive_deadline_on_misses",
        "action": "adaptive_deadline",
        "trigger": {"metric": "rounds.straggler_rate", "op": ">",
                    "threshold": 0.15},
        "params": {"quantile": 0.95, "margin": 1.5},
        "description": "fit the reporting deadline from observed "
                       "train_s instead of the static round_timeout",
    },
    {
        "name": "fedbuff_on_churn",
        "action": "fedbuff_fallback",
        "trigger": {"metric": "fleet.churn_frac", "op": ">",
                    "threshold": 0.34},
        "params": {"buffer_frac": 0.6},
        "description": "with a third of the active fleet flaky, finish "
                       "rounds on a FedBuff-style report buffer",
    },
    {
        "name": "pin_shapes_on_storm",
        "action": "pin_shapes",
        "trigger": {"alert": "recompile_storm"},
        "description": "pin batch shapes and quarantine storm offenders "
                       "while the recompile_storm alert fires",
    },
]


# ---------------------------------------------------------------------------
# Pure actuation helpers (unit-testable without an engine)


def weighted_sample(
    ids: Sequence[str],
    weights: Dict[str, float],
    k: int,
    rng,
) -> List[str]:
    """Sample ``k`` distinct ids with probability proportional to
    weight (Efraimidis–Spirakis A-Res: key = u^(1/w), take the top k).
    Deterministic under a seeded ``rng``; ids missing from ``weights``
    default to weight 1.0. Weights are floored at a tiny positive value
    so a mis-set weight can bias but never fully exclude a client."""
    k = max(0, min(int(k), len(ids)))
    if k == len(ids):
        return list(ids)
    keyed = []
    for cid in ids:
        w = max(1e-9, float(weights.get(cid, 1.0)))
        keyed.append((rng.random() ** (1.0 / w), cid))
    keyed.sort(key=lambda kv: kv[0], reverse=True)
    return [cid for _, cid in keyed[:k]]


def overprovision_count(
    k: int,
    n_available: int,
    miss_rate: float,
    *,
    epsilon_max: float = 0.5,
    gain: float = 1.0,
) -> Tuple[int, float]:
    """``(inflated_k, epsilon)``: invite ``ceil(k·(1+ε))`` with
    ``ε = min(epsilon_max, gain·miss_rate)``, capped by availability."""
    eps = min(float(epsilon_max), max(0.0, float(gain) * float(miss_rate)))
    inflated = int(math.ceil(k * (1.0 + eps)))
    return max(k, min(int(n_available), inflated)), eps


def fit_deadline(
    train_seconds: Iterable[float],
    *,
    quantile: float = 0.95,
    margin: float = 1.5,
    min_s: Optional[float] = 0.25,
    max_s: Optional[float] = None,
) -> Optional[float]:
    """Reporting deadline fit from per-client observed training times:
    ``clamp(quantile(train_s)·margin, min_s, max_s)``; None when no
    usable history exists (the caller keeps the static timeout)."""
    vals = sorted(
        float(v) for v in train_seconds
        if isinstance(v, (int, float)) and float(v) > 0.0
    )
    if not vals:
        return None
    d = _quantile(vals, min(1.0, max(0.0, float(quantile)))) * float(margin)
    if min_s is not None:
        d = max(d, float(min_s))
    if max_s is not None:
        d = min(d, float(max_s))
    return d


def derive_fleet_view(classified: Optional[Dict[str, dict]]) -> Dict[str, float]:
    """``fleet.*`` metric addresses from one
    :meth:`ClientLedger.classify_all` map — the classification half of
    the trigger namespace (the alert view supplies ``counter:`` /
    ``timer:`` / ``rounds.*``). Fractions are over *active* (non-
    ``inactive``) clients so a drained fleet doesn't dilute churn."""
    m: Dict[str, float] = {}
    if not classified:
        return m
    active = {
        cid: c for cid, c in classified.items()
        if isinstance(c, dict) and c.get("status") != "inactive"
    }
    m["fleet.clients"] = float(len(classified))
    m["fleet.active_clients"] = float(len(active))
    if not active:
        return m
    n = float(len(active))
    by_status: Dict[str, int] = {}
    for c in active.values():
        by_status[c.get("status", "?")] = by_status.get(
            c.get("status", "?"), 0) + 1
    for status in _BIASABLE_STATUSES:
        m[f"fleet.{status}_frac"] = by_status.get(status, 0) / n
    m["fleet.slow_or_flaky_frac"] = (
        by_status.get("slow", 0) + by_status.get("flaky", 0)
    ) / n
    # churn: clients that join rounds but keep missing the window —
    # exactly the flaky classification (+ degrading trending that way)
    m["fleet.churn_frac"] = (
        by_status.get("flaky", 0) + by_status.get("degrading", 0)
    ) / n
    m["fleet.storm_clients"] = float(sum(
        1 for c in active.values() if c.get("storms")
    ))
    return m


def read_runbooks_jsonl(path: str) -> Tuple[List[dict], int]:
    """Tolerant ``runbooks.jsonl`` reader — ``(events, n_torn)``."""
    from baton_tpu.utils.slog import read_rounds_jsonl

    return read_rounds_jsonl(path)


# ---------------------------------------------------------------------------
# Engine


@dataclass
class _ActState:
    state: str = "idle"          # idle | pending | active
    pending_since: Optional[float] = None
    active_since: Optional[float] = None
    cooldown_until: float = 0.0
    episodes: int = 0
    last_value: Any = None
    skip_reason: Optional[str] = None
    actuations: int = 0          # times the manager applied this rule
    history: List[str] = field(default_factory=list)


class RunbookEngine:
    """Steps every runbook rule's idle→active→idle machine against
    successive metric views + the alert engine's firing set.

    One engine per manager; :meth:`evaluate` runs on the same
    ``PeriodicTask`` tick as the alert engine (the runbook view is the
    alert view plus ``fleet.*``). The manager consults
    :meth:`actuation` on its invite/finish paths and reports each
    application back via :meth:`record_actuation` so the status
    snapshot shows rules that are active-but-never-applied (a trigger
    bound to a metric its node never emits, the skip_reason surface).
    """

    def __init__(
        self,
        rules: Optional[Iterable] = None,
        *,
        log_path: Optional[str] = None,
        metrics=None,
        node: str = "manager",
        now: Callable[[], float] = time.time,
    ) -> None:
        parsed: List[RunbookRule] = []
        for i, r in enumerate(rules or ()):
            rule = r if isinstance(r, RunbookRule) else RunbookRule.parse(
                r, ctx=f"runbook rule [{i}]"
            )
            parsed.append(rule)
        names = [r.name for r in parsed]
        dupes = sorted({n for n in names if names.count(n) > 1})
        if dupes:
            raise RunbookRuleError(f"duplicate runbook rule names: {dupes}")
        self.rules = parsed
        self.node = node
        self.metrics = metrics
        self._now = now
        self._log_path = log_path
        self._log_lock = threading.Lock()
        if log_path:
            os.makedirs(
                os.path.dirname(os.path.abspath(log_path)), exist_ok=True
            )
        self._states: Dict[str, _ActState] = {
            r.name: _ActState() for r in self.rules
        }

    # -- persistence ---------------------------------------------------
    def _append(self, record: dict) -> None:
        if not self._log_path:
            return
        data = json.dumps(record, default=repr) + "\n"
        with self._log_lock:
            with open(self._log_path, "a", encoding="utf-8") as fh:
                fh.write(data)
                fh.flush()

    def _inc(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name)

    def _emit(self, event: str, rule: RunbookRule, st: _ActState,
              now: float, **extra) -> dict:
        rec = {
            "ts": round(now, 6),
            "node": self.node,
            "event": event,
            "rule": rule.name,
            "action": rule.action,
            "trigger": rule.trigger_desc(),
            "value": st.last_value,
            "episode": st.episodes,
        }
        rec.update(extra)
        st.history = (st.history + [event])[-8:]
        self._append(rec)
        return rec

    # -- the tick ------------------------------------------------------
    def evaluate(
        self,
        view: Dict[str, float],
        firing: Sequence[str] = (),
    ) -> List[dict]:
        """Step every rule against one metric view and the currently-
        firing alert names. Returns the emitted transition events.
        Never raises on a bad rule/metric — per-rule failures are
        counted (``runbooks_eval_errors``) and held as skip_reason."""
        now = self._now()
        firing_set = set(firing)
        events: List[dict] = []
        for rule in self.rules:
            st = self._states[rule.name]
            try:
                events.extend(
                    self._step(rule, st, view, firing_set, now)
                )
            except Exception:
                self._inc("runbooks_eval_errors")
        if self.metrics is not None:
            self.metrics.set_gauge(
                "runbooks_active",
                sum(1 for s in self._states.values()
                    if s.state == "active"),
            )
        return events

    def _step(self, rule: RunbookRule, st: _ActState,
              view: Dict[str, float], firing_set: set,
              now: float) -> List[dict]:
        out: List[dict] = []
        if "alert" in rule.trigger:
            # ride the alert's own lifecycle: its clear_ratio hysteresis
            # already separates firing from resolved, so breach==hold
            breach = hold = rule.trigger["alert"] in firing_set
            st.last_value = 1.0 if breach else 0.0
            st.skip_reason = None
        else:
            value, skip = resolve_view_metric(view, rule._trig.metric)
            if value is None:
                st.skip_reason = skip
                return out  # not evaluable: hold state, try next tick
            st.skip_reason = None
            st.last_value = value
            breach = rule._trig.breaches(value)
            hold = rule._trig.still_breaching(value)
        if st.state == "idle":
            if breach and now >= st.cooldown_until:
                st.state = "pending"
                st.pending_since = now
                if rule.for_s <= 0:
                    out.append(self._enter(rule, st, now))
        elif st.state == "pending":
            if not breach:
                st.state = "idle"
                st.pending_since = None
            elif now - st.pending_since >= rule.for_s:
                out.append(self._enter(rule, st, now))
        elif st.state == "active":
            if not hold:
                st.state = "idle"
                st.active_since = None
                st.pending_since = None
                st.cooldown_until = now + rule.cooldown_s
                self._inc("runbooks_exited_total")
                out.append(self._emit(
                    "exited", rule, st, now,
                    cooldown_until=round(st.cooldown_until, 6),
                ))
        return out

    def _enter(self, rule: RunbookRule, st: _ActState, now: float) -> dict:
        st.state = "active"
        st.active_since = now
        st.episodes += 1
        self._inc("runbooks_entered_total")
        return self._emit("entered", rule, st, now, params=rule.params)

    # -- the actuation surface the manager consults --------------------
    def actuation(self, action: str) -> Optional[dict]:
        """The first ACTIVE rule for ``action`` as an explainability
        stub: ``{"action", "rule", "trigger", "value", "params"}`` —
        the manager applies it, extends it with the applied detail, and
        stamps it into the round's ``rounds.jsonl`` record. None when
        no rule for that action is active (the normal path)."""
        for rule in self.rules:
            if rule.action != action:
                continue
            st = self._states[rule.name]
            if st.state == "active":
                return {
                    "action": rule.action,
                    "rule": rule.name,
                    "trigger": rule.trigger_desc(),
                    "value": st.last_value,
                    "params": dict(rule.params),
                }
        return None

    def record_actuation(self, rule_name: str) -> None:
        """The manager applied this rule to a round."""
        st = self._states.get(rule_name)
        if st is not None:
            st.actuations += 1
        self._inc("runbooks_actuations_total")

    def active(self) -> List[str]:
        """Names of currently-active rules."""
        return [r.name for r in self.rules
                if self._states[r.name].state == "active"]

    # -- introspection -------------------------------------------------
    def status_snapshot(self) -> dict:
        """The ``GET /{name}/runbooks`` payload."""
        now = self._now()
        rules = []
        for rule in self.rules:
            st = self._states[rule.name]
            rules.append({
                "name": rule.name,
                "action": rule.action,
                "trigger": rule.trigger_desc(),
                "for_s": rule.for_s,
                "cooldown_s": rule.cooldown_s,
                "params": dict(rule.params),
                "description": rule.description,
                "state": st.state,
                "value": st.last_value,
                "episodes": st.episodes,
                "actuations": st.actuations,
                "active_since": st.active_since,
                "cooldown_until": st.cooldown_until or None,
                "skip_reason": st.skip_reason,
                "recent_transitions": list(st.history),
            })
        active = [r["name"] for r in rules if r["state"] == "active"]
        return {
            "node": self.node,
            "ts": round(now, 6),
            "rules": rules,
            "active": active,
            "summary": {
                "rules": len(rules),
                "active": len(active),
                "actuations": sum(r["actuations"] for r in rules),
            },
        }
