#!/usr/bin/env python
"""Gate flagship bench numbers against a committed baseline.

    python scripts/check_bench_slo.py --latest
    python scripts/check_bench_slo.py BENCH_r05.json

Reads one ``bench.py`` output record — either a raw record or a
``BENCH_rNN.json`` wrapper (its ``parsed`` block) — flattens it into the
``bench:`` SLO namespace (:func:`baton_tpu.loadgen.slo.derive_bench_metrics`)
and runs the same baseline-delta comparison the scenario gate uses, so a
BENCH_r03→r04-class perf dip (``fused_rounds_per_sec`` silently becoming
null, a flagship MFU sliding) fails CI instead of waiting for a
reviewer's eyeball. A number that is missing *with a recorded skip
reason* (``fused_skip_reason`` / ``degraded_reason``) reports as skipped
— unmeasured must name why; unmeasured without a reason regresses.

Exit codes: 0 pass, 1 regression, 2 config/input error.
"""

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_BASELINE = os.path.join("benchmarks", "baselines",
                                "bench_flagship.json")


def _latest_bench(root: str) -> str:
    cands = glob.glob(os.path.join(root, "BENCH_r*.json"))
    if not cands:
        raise FileNotFoundError(f"no BENCH_r*.json under {root}")

    def key(p):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    return max(cands, key=key)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python scripts/check_bench_slo.py",
        description="flagship bench baseline-delta gate",
    )
    ap.add_argument("bench", nargs="?", default=None,
                    help="bench output JSON (raw record or BENCH_rNN wrapper)")
    ap.add_argument("--latest", action="store_true",
                    help="gate the highest-numbered BENCH_r*.json in the "
                         "repo root")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--report", default=None,
                    help="write the full delta report JSON here")
    args = ap.parse_args(argv)

    from baton_tpu.loadgen.slo import check_bench_baseline, load_baseline
    from baton_tpu.loadgen.scenario import ScenarioError

    try:
        path = args.bench or (_latest_bench(".") if args.latest else None)
        if path is None:
            ap.error("pass a bench JSON or --latest")
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
        parsed = data.get("parsed") if isinstance(data.get("parsed"), dict) \
            else data
        baseline = load_baseline(args.baseline)
    except (OSError, ValueError, ScenarioError) as exc:
        print(f"bench gate error: {exc}", file=sys.stderr)
        return 2

    results, skips = check_bench_baseline(baseline, parsed)
    regressions = [r for r in results if r["regression"]]
    report = {
        "bench": path,
        "baseline": args.baseline,
        "regressions": len(regressions),
        "results": results,
        "skips": skips,
    }
    if args.report:
        os.makedirs(os.path.dirname(os.path.abspath(args.report)),
                    exist_ok=True)
        with open(args.report, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2)
            fh.write("\n")

    verdict = "PASS" if not regressions else "FAIL"
    print(f"[{verdict}] bench={path} baseline={args.baseline} "
          f"checked={len(results)} regressions={len(regressions)} "
          f"skipped={sum(1 for r in results if 'skipped' in (r.get('note') or ''))}")
    for r in results:
        note = r.get("note")
        if r["regression"]:
            print(f"  regression: {r['metric']} baseline={r['baseline']} "
                  f"observed={r['observed']} ({note or 'beyond tolerance'})")
        elif note:
            print(f"  {r['metric']}: {note}")
    return 0 if not regressions else 1


if __name__ == "__main__":
    sys.exit(main())
