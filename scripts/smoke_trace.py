"""CI smoke round with distributed tracing: one root manager, two edge
aggregators, and 2 in-process workers (one per edge) over real loopback
sockets, one federated round end to end, then export the round's merged
trace and SLO record as build artifacts.

Artifacts (``--artifacts DIR``, default ``./artifacts``):

* ``round_trace.json``  — Chrome ``trace_event`` export of the round
  (drop it into Perfetto / chrome://tracing); spans from all THREE
  tiers — manager, edges, workers — merged by traceparent;
* ``rounds.jsonl``      — the per-round SLO records;
* ``manager_metrics.json`` — the manager's full metrics snapshot
  (histogram timers with p50/p95/p99);
* ``edge_metrics.json`` — both edges' metrics snapshots.

Exits non-zero if the round fails, the trace is missing spans from any
tier of the federation (the edge hop must carry the traceparent both
ways), or the SLO record is absent — so a CI run that silently breaks
traceparent propagation fails here rather than in a dashboard weeks
later.

Run locally:  JAX_PLATFORMS=cpu python scripts/smoke_trace.py
"""

import argparse
import asyncio
import json
import logging
import os
import socket
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402
from aiohttp import web  # noqa: E402

from baton_tpu.core.training import make_local_trainer  # noqa: E402
from baton_tpu.data.synthetic import linear_client_data  # noqa: E402
from baton_tpu.models.linear import linear_regression_model  # noqa: E402
from baton_tpu.server.edge import EdgeAggregator  # noqa: E402
from baton_tpu.server.http_manager import Manager  # noqa: E402
from baton_tpu.server.http_worker import ExperimentWorker  # noqa: E402
from baton_tpu.utils.slog import setup_json_logging  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def _wait(cond, n=600, dt=0.05):
    for _ in range(n):
        if cond():
            return True
        await asyncio.sleep(dt)
    return cond()


async def _smoke(artifacts: str) -> int:
    import aiohttp

    name, mport, dim = "smoke", _free_port(), 10
    trace_dir = os.path.join(artifacts, "trace_spool")
    rounds_path = os.path.join(artifacts, "rounds.jsonl")

    model = linear_regression_model(dim)
    mapp = web.Application()
    exp = Manager(mapp).register_experiment(
        model, name=name,
        trace_dir=trace_dir, rounds_log_path=rounds_path,
    )
    mrunner = web.AppRunner(mapp)
    await mrunner.setup()
    await web.TCPSite(mrunner, "127.0.0.1", mport).start()

    # two edge aggregators between the workers and the root: the round
    # must traverse the full hierarchy (notify relay down, blob cache
    # serve, partial fold + ship up) with the traceparent intact
    runners = [mrunner]
    edges = []
    for i in range(2):
        eport = _free_port()
        eapp = web.Application()
        edge = EdgeAggregator(
            eapp, f"127.0.0.1:{mport}", name=name, port=eport,
            edge_name=f"e{i}", ship_settle_s=0.05, heartbeat_time=5.0,
        )
        erunner = web.AppRunner(eapp)
        await erunner.setup()
        await web.TCPSite(erunner, "127.0.0.1", eport).start()
        edges.append(edge)
        runners.append(erunner)

    trainer = make_local_trainer(linear_regression_model(dim),
                                 batch_size=32, learning_rate=0.02)
    nprng = np.random.default_rng(0)
    workers = []
    # one plain worker, one chunk-uploading worker — both upload paths
    # must carry the traceparent; each routes through its own edge
    for i, chunk in enumerate((None, 1 << 12)):
        wport = _free_port()
        data = linear_client_data(nprng, min_batches=2, max_batches=2)
        wapp = web.Application()
        w = ExperimentWorker(
            wapp, model, f"127.0.0.1:{mport}",
            name=name, port=wport, heartbeat_time=0.5,
            trainer=trainer,
            get_data=lambda d=data: (d, d["x"].shape[0]),
            outbox_backoff=(0.05, 0.4),
            upload_chunk_bytes=chunk,
            edge=f"127.0.0.1:{edges[i].port}",
        )
        wrunner = web.AppRunner(wapp)
        await wrunner.setup()
        await web.TCPSite(wrunner, "127.0.0.1", wport).start()
        workers.append(w)
        runners.append(wrunner)

    ok = True
    try:
        # 2 workers + 2 edges (each edge holds a client entry of its own)
        assert await _wait(lambda: len(exp.registry) == 4), \
            "workers/edges did not register"
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"http://127.0.0.1:{mport}/{name}/start_round?n_epoch=2"
            ) as resp:
                assert resp.status == 200, await resp.text()
        assert await _wait(lambda: exp.rounds.n_rounds == 1, n=1200), \
            "round did not complete"
        # worker spans arrive via the async upstream ship
        assert await _wait(lambda: all(
            w.metrics.snapshot()["counters"].get("trace_spans_shipped", 0)
            for w in workers
        )), "worker spans were not shipped"

        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"http://127.0.0.1:{mport}/{name}/rounds/0/trace"
            ) as resp:
                assert resp.status == 200, await resp.text()
                trace = await resp.json()
            async with session.get(
                f"http://127.0.0.1:{mport}/{name}/metrics"
            ) as resp:
                metrics = await resp.json()

        with open(os.path.join(artifacts, "round_trace.json"), "w") as fh:
            json.dump(trace, fh, indent=2)
        with open(os.path.join(artifacts, "manager_metrics.json"),
                  "w") as fh:
            json.dump(metrics, fh, indent=2)
        with open(os.path.join(artifacts, "edge_metrics.json"),
                  "w") as fh:
            json.dump({e.edge_name: e.metrics.snapshot() for e in edges},
                      fh, indent=2)

        services = {
            e["args"]["name"]
            for e in trace["traceEvents"] if e["ph"] == "M"
        }
        span_names = {
            e["name"] for e in trace["traceEvents"] if e["ph"] == "X"
        }
        assert any(s.startswith("manager#") for s in services), services
        assert sum(s.startswith("worker:") for s in services) == 2, services
        assert sum(s.startswith("edge:") for s in services) == 2, services
        for want in ("round", "round_setup", "notify", "local_train",
                     "upload", "ingest", "aggregate", "edge_relay",
                     "edge_partial_upload"):
            assert want in span_names, (want, span_names)
        mc = metrics["counters"]
        assert mc.get("updates_received_edge_partial") == 2, mc
        assert mc.get("updates_received") == 2, mc
        for e in edges:
            ec = e.metrics.snapshot()["counters"]
            assert ec.get("edge_partials_shipped") == 1, (e.edge_name, ec)
            assert ec.get("edge_updates_folded") == 1, (e.edge_name, ec)
        for tname, st in metrics["timers"].items():
            assert {"p50_s", "p95_s", "p99_s"} <= set(st), tname
        with open(rounds_path) as fh:
            records = [json.loads(ln) for ln in fh if ln.strip()]
        assert len(records) == 1 and records[0]["outcome"] == "completed", \
            records
        print(f"smoke ok: {len(span_names)} span kinds from "
              f"{len(services)} services; round "
              f"{records[0]['round']} {records[0]['duration_s']:.2f}s, "
              f"phases={sorted(records[0]['phase_s'])}")
    except AssertionError as exc:
        print(f"SMOKE FAILED: {exc}", file=sys.stderr)
        ok = False
    finally:
        for r in runners:
            await r.cleanup()
    return 0 if ok else 1


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--artifacts", default="artifacts")
    args = ap.parse_args()
    os.makedirs(args.artifacts, exist_ok=True)
    setup_json_logging(level=logging.INFO)
    sys.exit(asyncio.run(_smoke(args.artifacts)))
