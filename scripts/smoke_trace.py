"""CI smoke round with distributed tracing + the fleet health plane:
one root manager, two edge aggregators, and 4 in-process workers (two
per edge, one slowed 8x) over real loopback sockets, three federated
rounds end to end, then export the round trace, fleet health, metric
history, and SLO records as build artifacts.

Artifacts (``--artifacts DIR``, default ``./artifacts``):

* ``round_trace.json``  — Chrome ``trace_event`` export of the round
  the ``local_train`` p99 exemplar points at (drop it into Perfetto /
  chrome://tracing); spans from all THREE tiers merged by traceparent;
* ``rounds.jsonl``      — the per-round SLO records (now with
  ``straggler_why`` classification reasons);
* ``manager_metrics.json`` — the manager's full metrics snapshot
  (histogram timers with p50/p95/p99 and trace exemplars);
* ``edge_metrics.json`` — both edges' metrics snapshots;
* ``fleet_health.json`` — ``GET /fleet/health`` from the root and both
  edges (per-client anomaly classifications);
* ``metrics_history.json`` — ``GET /metrics/history`` from all three
  nodes (the timestamped snapshot rings);
* ``ops_console.json``  — one ``python -m baton_tpu.ops --once --json``
  poll of the live federation;
* ``compute_profile.json`` — the compute plane: every round's
  ``compute`` section from ``rounds.jsonl`` plus each worker's last
  ``compute_*`` gauges (throughput/steps measured on this CPU tier;
  MFU/HBM null-with-reason).

Exits non-zero if a round fails, the trace is missing spans from any
tier, the 8x-slowed worker is not classified ``slow``, the round
record does not name it with a reason, the ``local_train_s`` exemplar
does not resolve to a fetchable trace containing that worker's span,
the ops console probe fails, or compute telemetry is missing from any
tier (worker gauges, edge ledger, root round records).

Run locally:  JAX_PLATFORMS=cpu python scripts/smoke_trace.py
"""

import argparse
import asyncio
import json
import logging
import os
import socket
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402
from aiohttp import web  # noqa: E402

from baton_tpu.core.training import make_local_trainer  # noqa: E402
from baton_tpu.data.synthetic import linear_client_data  # noqa: E402
from baton_tpu.models.linear import linear_regression_model  # noqa: E402
from baton_tpu.server.edge import EdgeAggregator  # noqa: E402
from baton_tpu.server.http_manager import Manager  # noqa: E402
from baton_tpu.server.http_worker import ExperimentWorker  # noqa: E402
from baton_tpu.utils import tracing  # noqa: E402
from baton_tpu.utils.faults import FaultInjector  # noqa: E402
from baton_tpu.utils.slog import setup_json_logging  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def _wait(cond, n=600, dt=0.05):
    for _ in range(n):
        if cond():
            return True
        await asyncio.sleep(dt)
    return cond()


async def _get_json(session, url):
    async with session.get(url) as resp:
        assert resp.status == 200, (url, resp.status, await resp.text())
        return await resp.json()


async def _run_console_once(mport, name, edge_ports):
    """``python -m baton_tpu.ops --once --json`` against the live
    federation — the CI probe mode the console exists for."""
    edges = ",".join(
        f"http://127.0.0.1:{p}/{name}" for p in edge_ports
    )
    proc = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "baton_tpu.ops",
        "--root", f"http://127.0.0.1:{mport}/{name}",
        "--edges", edges, "--once", "--json",
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.PIPE,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    out, err = await asyncio.wait_for(proc.communicate(), timeout=120)
    assert proc.returncode == 0, (proc.returncode, err.decode()[-2000:])
    return json.loads(out.decode())


async def _smoke(artifacts: str) -> int:
    import aiohttp

    name, mport, dim = "smoke", _free_port(), 10
    trace_dir = os.path.join(artifacts, "trace_spool")
    rounds_path = os.path.join(artifacts, "rounds.jsonl")
    clients_path = os.path.join(artifacts, "clients.jsonl")

    model = linear_regression_model(dim)
    mapp = web.Application()
    exp = Manager(mapp).register_experiment(
        model, name=name,
        trace_dir=trace_dir, rounds_log_path=rounds_path,
        clients_log_path=clients_path,
        metrics_history_interval_s=0.5,
    )
    mrunner = web.AppRunner(mapp)
    await mrunner.setup()
    await web.TCPSite(mrunner, "127.0.0.1", mport).start()

    # two edge aggregators between the workers and the root: the round
    # must traverse the full hierarchy (notify relay down, blob cache
    # serve, partial fold + ship up) with the traceparent intact
    runners = [mrunner]
    edges = []
    for i in range(2):
        eport = _free_port()
        eapp = web.Application()
        edge = EdgeAggregator(
            eapp, f"127.0.0.1:{mport}", name=name, port=eport,
            edge_name=f"e{i}", ship_settle_s=0.05, heartbeat_time=5.0,
            metrics_history_interval_s=0.5,
        )
        erunner = web.AppRunner(eapp)
        await erunner.setup()
        await web.TCPSite(erunner, "127.0.0.1", eport).start()
        edges.append(edge)
        runners.append(erunner)

    trainer = make_local_trainer(linear_regression_model(dim),
                                 batch_size=32, learning_rate=0.02)
    nprng = np.random.default_rng(0)
    workers = []
    # four workers, two per edge: one chunk-uploading (both upload
    # paths must carry the traceparent) and one slowed 8x — the fleet
    # health plane must classify it `slow` from its self-reported
    # train timings. The last worker also carries a gated 503 fault so
    # round 3 can show a classification-backed straggler_why.
    slow_gate = {"on": False}
    for i, (chunk, scale) in enumerate(
        ((None, 1.0), (1 << 12, 1.0), (None, 1.0), (None, 8.0))
    ):
        wport = _free_port()
        data = linear_client_data(nprng, min_batches=2, max_batches=2)
        inj = FaultInjector()
        wapp = web.Application(middlewares=[inj.middleware])
        if scale > 1.0:
            inj.error("round_start", status=503,
                      gate=lambda: slow_gate["on"])
        w = ExperimentWorker(
            wapp, model, f"127.0.0.1:{mport}",
            name=name, port=wport, heartbeat_time=0.5,
            trainer=trainer,
            get_data=lambda d=data: (d, d["x"].shape[0]),
            outbox_backoff=(0.05, 0.4),
            upload_chunk_bytes=chunk,
            train_time_scale=scale,
            edge=f"127.0.0.1:{edges[i % 2].port}",
        )
        wrunner = web.AppRunner(wapp)
        await wrunner.setup()
        await web.TCPSite(wrunner, "127.0.0.1", wport).start()
        workers.append(w)
        runners.append(wrunner)
    slow_worker = workers[3]

    ok = True
    try:
        # 4 workers + 2 edges (each edge holds a client entry of its own)
        assert await _wait(lambda: len(exp.registry) == 6), \
            "workers/edges did not register"
        async with aiohttp.ClientSession() as session:
            # three rounds: 1-2 give the slow worker a reported train_s
            # history (=> `slow` classification), in 3 it refuses the
            # notify (503) so the round record's straggler_why has to
            # explain the miss FROM that history
            for rnd in range(3):
                slow_gate["on"] = rnd == 2
                before = exp.rounds.n_rounds
                async with session.get(
                    f"http://127.0.0.1:{mport}/{name}"
                    "/start_round?n_epoch=2"
                ) as resp:
                    assert resp.status == 200, await resp.text()
                assert await _wait(
                    lambda: exp.rounds.n_rounds > before, n=1200
                ), f"round {rnd} did not complete"
            slow_gate["on"] = False
            # worker spans arrive via the async upstream ship
            assert await _wait(lambda: all(
                w.metrics.snapshot()["counters"].get(
                    "trace_spans_shipped", 0
                )
                for w in workers
            )), "worker spans were not shipped"

            # -- fleet health plane ---------------------------------
            base = f"http://127.0.0.1:{mport}/{name}"
            health = {"root": await _get_json(session,
                                              f"{base}/fleet/health")}
            history = {"root": await _get_json(
                session, f"{base}/metrics/history"
            )}
            for e in edges:
                ebase = f"http://127.0.0.1:{e.port}/{name}"
                health[e.edge_name] = await _get_json(
                    session, f"{ebase}/fleet/health"
                )
                history[e.edge_name] = await _get_json(
                    session, f"{ebase}/metrics/history"
                )

            sick = health["root"]["clients"].get(slow_worker.client_id)
            assert sick is not None, health["root"]["clients"].keys()
            assert sick["status"] == "slow", sick
            assert "train_s median" in sick["reason"], sick
            for node, h in health.items():
                assert h["summary"]["total"] >= 1, (node, h)
            for node, h in history.items():
                assert h["samples"] >= 1, (node, h)

            # the slow worker's local_train_s p99 exemplar must point
            # at a fetchable round trace containing its span
            wt = slow_worker.metrics.snapshot()["timers"]
            ex = wt["local_train_s"].get("exemplar")
            assert ex and ex.get("trace_id"), wt["local_train_s"]
            with open(rounds_path) as fh:
                records = [json.loads(ln) for ln in fh if ln.strip()]
            by_trace = {
                tracing.make_trace_id(name, r["round"]): r["round"]
                for r in records
            }
            ex_round = by_trace.get(ex["trace_id"])
            assert ex_round is not None, (ex, sorted(by_trace.values()))
            trace = await _get_json(
                session, f"{base}/rounds/{ex_round}/trace"
            )
            dump = json.dumps(trace)
            assert "local_train" in dump, "exemplar trace has no train"
            assert slow_worker.client_id in dump, \
                "exemplar trace is missing the slow worker's span"

            metrics = await _get_json(session, f"{base}/metrics")

        # round 3's record must NAME the refusing worker with a
        # classification-backed reason derived from rounds 1-2
        why = records[-1].get("straggler_why") or {}
        assert slow_worker.client_id in why, (why, records[-1])
        assert why[slow_worker.client_id].startswith("slow:"), why

        # -- compute plane (all three tiers) ------------------------
        # root tier: every round record carries a valid compute
        # section — throughput/steps measured, MFU + peak HBM
        # null-with-reason on this CPU tier (never a bare null)
        from baton_tpu.obs.compute import validate_record
        for r in records:
            comp = r.get("compute")
            assert isinstance(comp, dict), ("round missing compute", r)
            assert validate_record(comp) == [], (comp, r["round"])
            assert comp["reporters"] >= 3, comp
            assert comp["steps"] and comp["steps"] > 0, comp
            assert comp["samples_per_sec_per_chip"] > 0, comp
            assert comp["compile_s"] is not None, comp
            assert comp["mfu"] is None and comp["mfu_reason"], comp
            assert comp["peak_hbm_gb"] is None \
                and comp["peak_hbm_gb_reason"], comp
        # worker tier: each worker exported its last round's gauges
        worker_compute = {}
        for w in workers:
            wg = w.metrics.snapshot()["gauges"]
            assert wg.get("compute_steps"), (w.client_id, wg)
            assert wg.get("compute_samples_per_sec_per_chip"), \
                (w.client_id, wg)
            worker_compute[w.client_id] = {
                k: v for k, v in wg.items() if k.startswith("compute_")
            }
        # edge tier: the compute record survived the edge fold — the
        # edge ledgers saw per-client compile_s observations
        for e in edges:
            eclients = health[e.edge_name]["clients"]
            assert any(
                i.get("compile_s") is not None for i in eclients.values()
            ), (e.edge_name, eclients)

        # -- ops console (CI probe mode) ----------------------------
        console = await _run_console_once(
            mport, name, [e.port for e in edges]
        )
        assert console["root"]["up"], console["root"]
        assert all(e["up"] for e in console["edges"]), console["edges"]
        assert console["root"]["health"]["clients"], console["root"]
        # the console sees the same compute gauges the manager exports
        cg = console["root"]["metrics"]["gauges"]
        mg = metrics["gauges"]
        for k in ("compute_reporters", "compute_steps",
                  "compute_samples_per_sec_per_chip"):
            assert cg.get(k) == mg.get(k) and cg.get(k), (k, cg, mg)

        with open(os.path.join(artifacts, "round_trace.json"), "w") as fh:
            json.dump(trace, fh, indent=2)
        with open(os.path.join(artifacts, "manager_metrics.json"),
                  "w") as fh:
            json.dump(metrics, fh, indent=2)
        with open(os.path.join(artifacts, "edge_metrics.json"),
                  "w") as fh:
            json.dump({e.edge_name: e.metrics.snapshot() for e in edges},
                      fh, indent=2)
        with open(os.path.join(artifacts, "fleet_health.json"),
                  "w") as fh:
            json.dump(health, fh, indent=2)
        with open(os.path.join(artifacts, "metrics_history.json"),
                  "w") as fh:
            json.dump(history, fh, indent=2)
        with open(os.path.join(artifacts, "ops_console.json"),
                  "w") as fh:
            json.dump(console, fh, indent=2)
        with open(os.path.join(artifacts, "compute_profile.json"),
                  "w") as fh:
            json.dump({
                "rounds": [
                    dict(r["compute"], round=r["round"]) for r in records
                ],
                "workers": worker_compute,
            }, fh, indent=2)

        services = {
            e["args"]["name"]
            for e in trace["traceEvents"] if e["ph"] == "M"
        }
        span_names = {
            e["name"] for e in trace["traceEvents"] if e["ph"] == "X"
        }
        assert any(s.startswith("manager#") for s in services), services
        assert sum(s.startswith("edge:") for s in services) == 2, services
        for want in ("round", "round_setup", "notify", "local_train",
                     "upload", "ingest", "aggregate", "edge_relay",
                     "edge_partial_upload"):
            assert want in span_names, (want, span_names)
        mc = metrics["counters"]
        # 2 partials per round x 3 rounds (each edge ships one)
        assert mc.get("updates_received_edge_partial") == 6, mc
        assert mc.get("fleet_observations", 0) > 0, mc
        for e in edges:
            ec = e.metrics.snapshot()["counters"]
            assert ec.get("edge_partials_shipped") == 3, (e.edge_name, ec)
        for tname, st in metrics["timers"].items():
            assert {"p50_s", "p95_s", "p99_s"} <= set(st), tname
        # round_s carries a round-trace exemplar too
        assert metrics["timers"]["round_s"].get("exemplar"), \
            metrics["timers"]["round_s"]
        assert len(records) == 3 and all(
            r["outcome"] == "completed" for r in records
        ), records
        assert os.path.exists(clients_path), "clients.jsonl not written"
        print(f"smoke ok: {len(span_names)} span kinds from "
              f"{len(services)} services; {len(records)} rounds; "
              f"slow worker {slow_worker.client_id} classified "
              f"`{sick['status']}` ({sick['reason']}); "
              f"why[round3]={why[slow_worker.client_id]!r}")
    except AssertionError as exc:
        print(f"SMOKE FAILED: {exc}", file=sys.stderr)
        ok = False
    finally:
        for r in runners:
            await r.cleanup()
    return 0 if ok else 1


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--artifacts", default="artifacts")
    args = ap.parse_args()
    os.makedirs(args.artifacts, exist_ok=True)
    setup_json_logging(level=logging.INFO)
    sys.exit(asyncio.run(_smoke(args.artifacts)))
