"""CI smoke round with distributed tracing, the fleet health plane,
and the alerting plane: one root manager, two edge aggregators, and 4
in-process workers (two per edge, one slowed 8x) over real loopback
sockets, four federated rounds end to end, then export the round
trace, fleet health, metric history, alert lifecycle, forensics
bundle, and SLO records as build artifacts.

Round 2 (0-based) is the straggler round: the slow worker's UPLOAD
path is gated 503 at every hop and the round is force-ended while its
edge's partial is still unshipped, so the root records real stragglers
and the ``straggler_rate`` alert must walk pending -> firing (with
``capture: true`` arming a forensics bundle for the next round close).
Round 3 is clean again, so the alert must resolve and the bundle must
land with every evidence section present-or-reasoned.

Artifacts (``--artifacts DIR``, default ``./artifacts``):

* ``round_trace.json``  — Chrome ``trace_event`` export of the round
  the ``local_train`` p99 exemplar points at (drop it into Perfetto /
  chrome://tracing); spans from all THREE tiers merged by traceparent;
* ``rounds.jsonl``      — the per-round SLO records (now with
  ``straggler_why`` classification reasons);
* ``alerts.jsonl``      — the crash-safe alert lifecycle stream
  (pending/firing/resolved transition events);
* ``alerts_status.json`` — ``GET /{name}/alerts`` from the root and
  both edges at the end of the run;
* ``forensics/<digest>.json`` — the anomaly-triggered forensics
  bundle, content-addressed, written by the manager itself;
* ``forensics_manifest.json`` — the same bundle as fetched back over
  ``GET /{name}/forensics/{digest}``;
* ``manager_metrics.json`` — the manager's full metrics snapshot
  (histogram timers with p50/p95/p99 and trace exemplars);
* ``edge_metrics.json`` — both edges' metrics snapshots;
* ``fleet_health.json`` — ``GET /fleet/health`` from the root and both
  edges (per-client anomaly classifications);
* ``metrics_history.json`` — ``GET /metrics/history`` from all three
  nodes (the timestamped snapshot rings);
* ``ops_console.json``  — one ``python -m baton_tpu.ops --once --json``
  poll of the live federation (plus ``ops_console_firing.json``, the
  poll taken while the page alert was firing — exit code 1);
* ``compute_profile.json`` — the compute plane: every round's
  ``compute`` section from ``rounds.jsonl`` plus each worker's last
  ``compute_*`` gauges (throughput/steps measured on this CPU tier;
  MFU/HBM null-with-reason).

Exits non-zero if a round fails, the trace is missing spans from any
tier, the 8x-slowed worker is not classified ``slow``, the straggler
round's record does not name it with a reason, the ``straggler_rate``
alert does not fire within a couple of evaluation ticks (or fails to
resolve after the clean round), the forensics bundle is missing or
fails manifest validation, the ops console probe does not exit 1
while the page alert is firing (and 0 after it resolves), the
``local_train_s`` exemplar does not resolve to a fetchable trace
containing the slow worker's span, or compute telemetry is missing
from any tier (worker gauges, edge ledger, root round records).

Run locally:  JAX_PLATFORMS=cpu python scripts/smoke_trace.py
"""

import argparse
import asyncio
import json
import logging
import os
import socket
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402
from aiohttp import web  # noqa: E402

from baton_tpu.core.training import make_local_trainer  # noqa: E402
from baton_tpu.data.synthetic import linear_client_data  # noqa: E402
from baton_tpu.models.linear import linear_regression_model  # noqa: E402
from baton_tpu.obs.alerts import read_alerts_jsonl  # noqa: E402
from baton_tpu.obs.forensics import (  # noqa: E402
    EVIDENCE_SECTIONS, validate_manifest,
)
from baton_tpu.server.edge import EdgeAggregator  # noqa: E402
from baton_tpu.server.http_manager import Manager  # noqa: E402
from baton_tpu.server.http_worker import ExperimentWorker  # noqa: E402
from baton_tpu.utils import tracing  # noqa: E402
from baton_tpu.utils.faults import FaultInjector  # noqa: E402
from baton_tpu.utils.slog import setup_json_logging  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def _wait(cond, n=600, dt=0.05):
    for _ in range(n):
        if cond():
            return True
        await asyncio.sleep(dt)
    return cond()


async def _get_json(session, url):
    async with session.get(url) as resp:
        assert resp.status == 200, (url, resp.status, await resp.text())
        return await resp.json()


async def _run_console_once(mport, name, edge_ports, expect_rc=0):
    """``python -m baton_tpu.ops --once --json`` against the live
    federation — the CI probe mode the console exists for. The probe
    exits 1 while a ``page``-severity alert is firing anywhere in the
    fleet, so the caller states the return code it expects."""
    edges = ",".join(
        f"http://127.0.0.1:{p}/{name}" for p in edge_ports
    )
    proc = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "baton_tpu.ops",
        "--root", f"http://127.0.0.1:{mport}/{name}",
        "--edges", edges, "--once", "--json",
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.PIPE,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    out, err = await asyncio.wait_for(proc.communicate(), timeout=120)
    assert proc.returncode == expect_rc, \
        (proc.returncode, expect_rc, err.decode()[-2000:])
    return json.loads(out.decode())


async def _smoke(artifacts: str) -> int:
    import aiohttp

    name, mport, dim = "smoke", _free_port(), 10
    trace_dir = os.path.join(artifacts, "trace_spool")
    rounds_path = os.path.join(artifacts, "rounds.jsonl")
    clients_path = os.path.join(artifacts, "clients.jsonl")
    alerts_path = os.path.join(artifacts, "alerts.jsonl")
    forensics_dir = os.path.join(artifacts, "forensics")

    model = linear_regression_model(dim)
    # the straggler round gates the slow worker's upload 503 at every
    # hop it could take (its edge, and the root if it fails over), so
    # the update can neither fold nor land direct
    minj = FaultInjector()
    mapp = web.Application(middlewares=[minj.middleware])
    exp = Manager(mapp).register_experiment(
        model, name=name,
        trace_dir=trace_dir, rounds_log_path=rounds_path,
        clients_log_path=clients_path,
        metrics_history_interval_s=0.5,
        # a page-severity straggler alert with capture: the smoke
        # drives its full pending -> firing -> resolved lifecycle and
        # the forensics bundle it arms. threshold 0.1 so the single
        # force-ended round (window 1) is an unambiguous breach.
        alert_rules=[{
            "name": "straggler_rate",
            "metric": "rounds.straggler_rate",
            "op": ">", "threshold": 0.1, "for_s": 0.0,
            "cooldown_s": 5.0, "severity": "page", "capture": True,
        }],
        alerts_log_path=alerts_path,
        alerts_interval_s=0.2,
        alerts_rounds_window=1,
        forensics_dir=forensics_dir,
    )
    mrunner = web.AppRunner(mapp)
    await mrunner.setup()
    await web.TCPSite(mrunner, "127.0.0.1", mport).start()

    # two edge aggregators between the workers and the root: the round
    # must traverse the full hierarchy (notify relay down, blob cache
    # serve, partial fold + ship up) with the traceparent intact
    runners = [mrunner]
    edges = []
    einjs = []
    for i in range(2):
        eport = _free_port()
        einj = FaultInjector()
        einjs.append(einj)
        eapp = web.Application(middlewares=[einj.middleware])
        edge = EdgeAggregator(
            eapp, f"127.0.0.1:{mport}", name=name, port=eport,
            edge_name=f"e{i}", ship_settle_s=0.05, heartbeat_time=5.0,
            metrics_history_interval_s=0.5,
        )
        erunner = web.AppRunner(eapp)
        await erunner.setup()
        await web.TCPSite(erunner, "127.0.0.1", eport).start()
        edges.append(edge)
        runners.append(erunner)

    trainer = make_local_trainer(linear_regression_model(dim),
                                 batch_size=32, learning_rate=0.02)
    nprng = np.random.default_rng(0)
    workers = []
    # four workers, two per edge: one chunk-uploading (both upload
    # paths must carry the traceparent) and one slowed 8x — the fleet
    # health plane must classify it `slow` from its self-reported
    # train timings. The slow worker is also the straggler-round
    # victim: it ACKS the broadcast (so it IS a round participant)
    # but its upload is gated 503 below.
    slow_gate = {"on": False}
    for i, (chunk, scale) in enumerate(
        ((None, 1.0), (1 << 12, 1.0), (None, 1.0), (None, 8.0))
    ):
        wport = _free_port()
        data = linear_client_data(nprng, min_batches=2, max_batches=2)
        wapp = web.Application()
        w = ExperimentWorker(
            wapp, model, f"127.0.0.1:{mport}",
            name=name, port=wport, heartbeat_time=0.5,
            trainer=trainer,
            get_data=lambda d=data: (d, d["x"].shape[0]),
            outbox_backoff=(0.05, 0.4),
            upload_chunk_bytes=chunk,
            train_time_scale=scale,
            edge=f"127.0.0.1:{edges[i % 2].port}",
        )
        wrunner = web.AppRunner(wapp)
        await wrunner.setup()
        await web.TCPSite(wrunner, "127.0.0.1", wport).start()
        workers.append(w)
        runners.append(wrunner)
    slow_worker = workers[3]

    ok = True
    try:
        # 4 workers + 2 edges (each edge holds a client entry of its own)
        assert await _wait(lambda: len(exp.registry) == 6), \
            "workers/edges did not register"
        # straggler induction: a 503'd round_start would silently drop
        # the worker from the round's participant set (no straggler
        # recorded), so the gate sits on the UPLOAD path instead — the
        # worker acks, trains, and then cannot report. Installed only
        # now: client ids are server-assigned at registration.
        for inj in (minj, *einjs):
            inj.error(f"update?client_id={slow_worker.client_id}",
                      status=503, gate=lambda: slow_gate["on"])
        async with aiohttp.ClientSession() as session:
            # four rounds: 0-1 give the slow worker a reported train_s
            # history (=> `slow` classification). 2 is the straggler
            # round: its upload is gated 503 and the round force-ended
            # while e1 (its edge) still holds an unshipped partial, so
            # the root records real stragglers, straggler_why explains
            # the slow worker FROM its history, and the straggler_rate
            # alert fires (arming forensics). 3 is clean again: the
            # alert resolves and the bundle is captured at round close.
            console_firing = None
            for rnd in range(4):
                if rnd == 2:
                    slow_gate["on"] = True
                before = exp.rounds.n_rounds
                async with session.get(
                    f"http://127.0.0.1:{mport}/{name}"
                    "/start_round?n_epoch=2"
                ) as resp:
                    assert resp.status == 200, await resp.text()
                if rnd == 2:
                    # wait until every deliverable update landed — e0's
                    # partial (w0+w2) reached the root and w1's fold was
                    # accepted by e1 — then end the round under the
                    # still-gated slow worker. e1's partial never ships:
                    # its contributors surface as stragglers at the root.
                    covered = {workers[0].client_id, workers[2].client_id}
                    assert await _wait(lambda: (
                        covered <= set(exp.rounds.client_responses)
                        and workers[1].metrics.snapshot()["counters"].get(
                            "updates_delivered", 0) == 3
                    ), n=1200), "straggler round never quiesced"
                    async with session.get(
                        f"http://127.0.0.1:{mport}/{name}/end_round"
                    ) as resp:
                        assert resp.status == 200, await resp.text()
                elif rnd == 3:
                    # the round-3 broadcast supersedes the slow worker's
                    # stuck round-2 upload (and rolls e1, abandoning the
                    # stale partial); only then is the gate released so
                    # its round-3 update can land cleanly
                    assert await _wait(
                        lambda: slow_worker.metrics.snapshot()[
                            "counters"
                        ].get("updates_abandoned_superseded", 0) >= 1,
                        n=1200,
                    ), "stale straggler upload was not superseded"
                    slow_gate["on"] = False
                assert await _wait(
                    lambda: exp.rounds.n_rounds > before, n=1200
                ), f"round {rnd} did not complete"
                if rnd == 1:
                    # classify NOW, from the rounds-0/1 history alone:
                    # three near-identical peers vs one 8x-padded
                    # outlier is the cleanest cross-section this run
                    # ever has (MAD exactly 0 -> the floor applies and
                    # the robust z is enormous). Later rounds mix in
                    # the straggler gap, the console subprocess, and
                    # the forensics capture — any of which can spike a
                    # FAST worker's wall time and flatten the z-score.
                    sick = None
                    for _ in range(40):
                        h = await _get_json(
                            session,
                            f"http://127.0.0.1:{mport}/{name}"
                            "/fleet/health",
                        )
                        sick = h["clients"].get(slow_worker.client_id)
                        if sick and sick["status"] == "slow":
                            break
                        await asyncio.sleep(0.05)
                    assert sick is not None and sick["status"] == "slow", \
                        sick
                    assert "train_s median" in sick["reason"], sick
                if rnd == 2:
                    # the straggler record lands synchronously at
                    # end_round; the alert engine evaluates every 0.2s
                    # and the rule has no hold, so firing must follow
                    # within a couple of ticks
                    assert await _wait(
                        lambda: "straggler_rate" in exp.alerts.firing(),
                        n=40, dt=0.05,
                    ), exp.alerts.status_snapshot()
                    # the console probe exits 1 while a page alert fires
                    console_firing = await _run_console_once(
                        mport, name, [e.port for e in edges], expect_rc=1,
                    )
            # round 3's clean record empties the one-round window: the
            # alert must resolve, and the firing's armed capture must
            # have produced a forensics bundle at round close
            assert await _wait(
                lambda: exp.alerts.firing() == [], n=40, dt=0.05
            ), exp.alerts.status_snapshot()
            assert await _wait(lambda: len(exp.forensics) >= 1), \
                "forensics bundle not captured"
            # worker spans arrive via the async upstream ship
            assert await _wait(lambda: all(
                w.metrics.snapshot()["counters"].get(
                    "trace_spans_shipped", 0
                )
                for w in workers
            )), "worker spans were not shipped"

            # -- fleet health plane ---------------------------------
            base = f"http://127.0.0.1:{mport}/{name}"
            health = {"root": await _get_json(session,
                                              f"{base}/fleet/health")}
            history = {"root": await _get_json(
                session, f"{base}/metrics/history"
            )}
            for e in edges:
                ebase = f"http://127.0.0.1:{e.port}/{name}"
                health[e.edge_name] = await _get_json(
                    session, f"{ebase}/fleet/health"
                )
                history[e.edge_name] = await _get_json(
                    session, f"{ebase}/metrics/history"
                )

            # the `slow` classification itself was asserted after round
            # 1 (see above, before the noisy tail rounds); here the
            # ledger must still carry the client, now with its
            # straggler-round outcome folded in
            end_state = health["root"]["clients"].get(slow_worker.client_id)
            assert end_state is not None, health["root"]["clients"].keys()
            assert end_state["straggled"] >= 1, end_state
            for node, h in health.items():
                assert h["summary"]["total"] >= 1, (node, h)
            for node, h in history.items():
                assert h["samples"] >= 1, (node, h)

            # the slow worker's local_train_s p99 exemplar must point
            # at a fetchable round trace containing its span
            wt = slow_worker.metrics.snapshot()["timers"]
            ex = wt["local_train_s"].get("exemplar")
            assert ex and ex.get("trace_id"), wt["local_train_s"]
            with open(rounds_path) as fh:
                records = [json.loads(ln) for ln in fh if ln.strip()]
            by_trace = {
                tracing.make_trace_id(name, r["round"]): r["round"]
                for r in records
            }
            ex_round = by_trace.get(ex["trace_id"])
            assert ex_round is not None, (ex, sorted(by_trace.values()))
            trace = await _get_json(
                session, f"{base}/rounds/{ex_round}/trace"
            )
            dump = json.dumps(trace)
            assert "local_train" in dump, "exemplar trace has no train"
            assert slow_worker.client_id in dump, \
                "exemplar trace is missing the slow worker's span"

            metrics = await _get_json(session, f"{base}/metrics")

            # -- alerting plane -------------------------------------
            # alert status from all three tiers, the forensics index,
            # and the bundle itself fetched back over HTTP
            alerts_status = {
                "root": await _get_json(session, f"{base}/alerts")
            }
            for e in edges:
                alerts_status[e.edge_name] = await _get_json(
                    session, f"http://127.0.0.1:{e.port}/{name}/alerts"
                )
            findex = (await _get_json(session, f"{base}/forensics"))
            bundles = findex["bundles"]
            assert bundles and bundles[0]["rule"] == "straggler_rate", \
                findex
            manifest = await _get_json(
                session, f"{base}/forensics/{bundles[0]['digest']}"
            )

        assert alerts_status["root"]["node"] == "manager", alerts_status
        assert {r["name"] for r in alerts_status["root"]["rules"]} \
            == {"straggler_rate"}, alerts_status
        for e in edges:
            es = alerts_status[e.edge_name]
            assert es["node"] == f"edge:{e.edge_name}", es
            assert es["summary"]["firing"] == 0, es
        # the bundle contract: every evidence section present or
        # excused (the null-with-reason invariant, end to end), and
        # the content-addressed file on disk for artifact upload
        assert validate_manifest(manifest) == [], manifest
        body = manifest["sections"]
        for section in EVIDENCE_SECTIONS:
            assert section in body, section
            if body[section] is None:
                assert body[f"{section}_reason"], section
        assert manifest["rule"] == "straggler_rate", manifest
        assert manifest["severity"] == "page", manifest
        assert body["round_trace"]["traceEvents"], "bundle trace empty"
        assert os.path.exists(
            os.path.join(forensics_dir, f"{manifest['digest']}.json")
        ), "forensics bundle not persisted"
        # the crash-safe lifecycle stream walked the full state machine
        events, torn = read_alerts_jsonl(alerts_path)
        assert torn == 0, (torn, alerts_path)
        seq = [e["event"] for e in events
               if e.get("rule") == "straggler_rate"
               and e["event"] != "forensics"]
        assert seq == ["pending", "firing", "resolved"], events
        fire = next(e for e in events if e["event"] == "firing")
        assert fire["severity"] == "page" and fire["capture_armed"], fire
        forensic_events = [e for e in events if e["event"] == "forensics"]
        assert len(forensic_events) == 1, events
        assert forensic_events[0]["digest"] == manifest["digest"], events
        # the firing-window console poll carried the alert in its JSON
        assert console_firing is not None
        assert any(
            r.get("name") == "straggler_rate" and r.get("state") == "firing"
            for r in console_firing["root"]["alerts"]["rules"]
        ), console_firing["root"].get("alerts")

        # the straggler round's record must NAME the gated worker with
        # a classification-backed reason derived from rounds 1-2
        why = records[2].get("straggler_why") or {}
        assert slow_worker.client_id in why, (why, records[2])
        assert why[slow_worker.client_id].startswith("slow:"), why

        # -- compute plane (all three tiers) ------------------------
        # root tier: every round record carries a valid compute
        # section — throughput/steps measured, MFU + peak HBM
        # null-with-reason on this CPU tier (never a bare null)
        from baton_tpu.obs.compute import validate_record
        for i, r in enumerate(records):
            comp = r.get("compute")
            assert isinstance(comp, dict), ("round missing compute", r)
            assert validate_record(comp) == [], (comp, r["round"])
            # the force-ended straggler round only hears from e0's
            # partial (two workers); every other round hears all four
            assert comp["reporters"] >= (2 if i == 2 else 3), (i, comp)
            assert comp["steps"] and comp["steps"] > 0, comp
            assert comp["samples_per_sec_per_chip"] > 0, comp
            assert comp["compile_s"] is not None, comp
            assert comp["mfu"] is None and comp["mfu_reason"], comp
            assert comp["peak_hbm_gb"] is None \
                and comp["peak_hbm_gb_reason"], comp
        # worker tier: each worker exported its last round's gauges
        worker_compute = {}
        for w in workers:
            wg = w.metrics.snapshot()["gauges"]
            assert wg.get("compute_steps"), (w.client_id, wg)
            assert wg.get("compute_samples_per_sec_per_chip"), \
                (w.client_id, wg)
            worker_compute[w.client_id] = {
                k: v for k, v in wg.items() if k.startswith("compute_")
            }
        # edge tier: the compute record survived the edge fold — the
        # edge ledgers saw per-client compile_s observations
        for e in edges:
            eclients = health[e.edge_name]["clients"]
            assert any(
                i.get("compile_s") is not None for i in eclients.values()
            ), (e.edge_name, eclients)

        # -- ops console (CI probe mode) ----------------------------
        console = await _run_console_once(
            mport, name, [e.port for e in edges]
        )
        assert console["root"]["up"], console["root"]
        assert all(e["up"] for e in console["edges"]), console["edges"]
        assert console["root"]["health"]["clients"], console["root"]
        # the console sees the same compute gauges the manager exports
        cg = console["root"]["metrics"]["gauges"]
        mg = metrics["gauges"]
        for k in ("compute_reporters", "compute_steps",
                  "compute_samples_per_sec_per_chip"):
            assert cg.get(k) == mg.get(k) and cg.get(k), (k, cg, mg)

        with open(os.path.join(artifacts, "round_trace.json"), "w") as fh:
            json.dump(trace, fh, indent=2)
        with open(os.path.join(artifacts, "manager_metrics.json"),
                  "w") as fh:
            json.dump(metrics, fh, indent=2)
        with open(os.path.join(artifacts, "edge_metrics.json"),
                  "w") as fh:
            json.dump({e.edge_name: e.metrics.snapshot() for e in edges},
                      fh, indent=2)
        with open(os.path.join(artifacts, "fleet_health.json"),
                  "w") as fh:
            json.dump(health, fh, indent=2)
        with open(os.path.join(artifacts, "metrics_history.json"),
                  "w") as fh:
            json.dump(history, fh, indent=2)
        with open(os.path.join(artifacts, "ops_console.json"),
                  "w") as fh:
            json.dump(console, fh, indent=2)
        with open(os.path.join(artifacts, "ops_console_firing.json"),
                  "w") as fh:
            json.dump(console_firing, fh, indent=2)
        with open(os.path.join(artifacts, "alerts_status.json"),
                  "w") as fh:
            json.dump(alerts_status, fh, indent=2)
        with open(os.path.join(artifacts, "forensics_manifest.json"),
                  "w") as fh:
            json.dump(manifest, fh, indent=2)
        with open(os.path.join(artifacts, "compute_profile.json"),
                  "w") as fh:
            json.dump({
                "rounds": [
                    dict(r["compute"], round=r["round"]) for r in records
                ],
                "workers": worker_compute,
            }, fh, indent=2)

        services = {
            e["args"]["name"]
            for e in trace["traceEvents"] if e["ph"] == "M"
        }
        span_names = {
            e["name"] for e in trace["traceEvents"] if e["ph"] == "X"
        }
        assert any(s.startswith("manager#") for s in services), services
        assert sum(s.startswith("edge:") for s in services) == 2, services
        for want in ("round", "round_setup", "notify", "local_train",
                     "upload", "ingest", "aggregate", "edge_relay",
                     "edge_partial_upload"):
            assert want in span_names, (want, span_names)
        mc = metrics["counters"]
        # 2 partials per round x 4 rounds, minus e1's straggler-round
        # partial (force-ended unshipped, abandoned at the next roll)
        assert mc.get("updates_received_edge_partial") == 7, mc
        assert mc.get("fleet_observations", 0) > 0, mc
        e0c = edges[0].metrics.snapshot()["counters"]
        e1c = edges[1].metrics.snapshot()["counters"]
        assert e0c.get("edge_partials_shipped") == 4, e0c
        assert e1c.get("edge_partials_shipped") == 3, e1c
        assert e1c.get("edge_partials_abandoned") == 1, e1c
        for tname, st in metrics["timers"].items():
            assert {"p50_s", "p95_s", "p99_s"} <= set(st), tname
        # round_s carries a round-trace exemplar too
        assert metrics["timers"]["round_s"].get("exemplar"), \
            metrics["timers"]["round_s"]
        assert len(records) == 4 and all(
            r["outcome"] == "completed" for r in records
        ), records
        assert os.path.exists(clients_path), "clients.jsonl not written"
        print(f"smoke ok: {len(span_names)} span kinds from "
              f"{len(services)} services; {len(records)} rounds; "
              f"slow worker {slow_worker.client_id} classified "
              f"`{sick['status']}` ({sick['reason']}); "
              f"why[straggler round]={why[slow_worker.client_id]!r}; "
              f"alert lifecycle {seq} with forensics bundle "
              f"{manifest['digest'][:12]}…")
    except AssertionError as exc:
        print(f"SMOKE FAILED: {exc}", file=sys.stderr)
        ok = False
    finally:
        for r in runners:
            await r.cleanup()
    return 0 if ok else 1


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--artifacts", default="artifacts")
    args = ap.parse_args()
    os.makedirs(args.artifacts, exist_ok=True)
    setup_json_logging(level=logging.INFO)
    sys.exit(asyncio.run(_smoke(args.artifacts)))
