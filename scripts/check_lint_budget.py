#!/usr/bin/env python
"""Lint wall-time budget + incremental-cache gate for CI.

Runs batonlint twice over the same tree with a shared summary cache:

  1. cold — empty cache, writes the JSON/SARIF artifacts CI uploads
  2. warm — same invocation again; every per-file summary must come
     out of ``.batonlint_cache.json`` (hits == files, misses == 0)

and fails the job when either run exceeds its wall-time budget, the
second run missed the cache, or the SARIF artifact is missing rule
metadata for the execution-context rules (BTL005/BTL006/BTL007 — the
driver descriptors code-scanning UIs key on). That pins three
properties: the whole-program analysis stays cheap enough to run
before the pytest budget, the content-hash cache actually delivers
incremental reruns instead of silently recomputing everything, and
the context rules are registered in the build CI actually ran.

Exit codes: 0 all gates pass, 1 a gate failed, 2 lint itself found
problems or crashed (the lint step's own failure mode, surfaced as-is).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import subprocess
import sys
import time
from typing import List, Optional


def _run_lint(
    paths: List[str],
    json_out: pathlib.Path,
    cache: pathlib.Path,
    sarif: Optional[pathlib.Path],
) -> float:
    cmd = [
        sys.executable,
        "-m",
        "baton_tpu.analysis",
        *paths,
        "--json-out",
        str(json_out),
        "--cache",
        str(cache),
    ]
    if sarif is not None:
        cmd += ["--sarif", str(sarif)]
    t0 = time.monotonic()
    proc = subprocess.run(cmd)
    elapsed = time.monotonic() - t0
    if proc.returncode != 0:
        print(
            f"check_lint_budget: lint exited {proc.returncode}; "
            "fix findings (or the crash) before gating on timing",
            file=sys.stderr,
        )
        sys.exit(2)
    return elapsed


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "paths", nargs="*", default=["baton_tpu"], help="lint targets"
    )
    parser.add_argument(
        "--budget-seconds",
        type=float,
        default=60.0,
        help="max wall time for the cold run (warm gets the same cap)",
    )
    parser.add_argument(
        "--artifacts",
        default="artifacts",
        help="directory for batonlint-report.json / batonlint.sarif / "
        "lint_budget.json",
    )
    args = parser.parse_args(argv)

    art = pathlib.Path(args.artifacts)
    art.mkdir(parents=True, exist_ok=True)
    cache = art / "batonlint_cache.json"
    if cache.exists():
        cache.unlink()

    cold_json = art / "batonlint-report.json"
    warm_json = art / "batonlint-report-warm.json"
    cold_s = _run_lint(args.paths, cold_json, cache, art / "batonlint.sarif")
    warm_s = _run_lint(args.paths, warm_json, cache, None)

    cold = json.loads(cold_json.read_text())
    warm = json.loads(warm_json.read_text())
    failures: List[str] = []
    for label, elapsed in (("cold", cold_s), ("warm", warm_s)):
        if elapsed > args.budget_seconds:
            failures.append(
                f"{label} lint run took {elapsed:.1f}s "
                f"> budget {args.budget_seconds:.1f}s"
            )
    warm_cache = warm.get("cache") or {}
    files = warm.get("files_checked", 0)
    if warm_cache.get("misses", -1) != 0 or warm_cache.get("hits") != files:
        failures.append(
            "warm run did not come from cache: "
            f"hits={warm_cache.get('hits')} misses={warm_cache.get('misses')} "
            f"files={files}"
        )

    sarif_path = art / "batonlint.sarif"
    try:
        sarif = json.loads(sarif_path.read_text())
        sarif_rules = {
            r.get("id")
            for run in sarif.get("runs", [])
            for r in run.get("tool", {}).get("driver", {}).get("rules", [])
        }
    except (OSError, ValueError) as exc:
        sarif_rules = set()
        failures.append(f"SARIF artifact unreadable: {exc}")
    missing = {"BTL005", "BTL006", "BTL007"} - sarif_rules
    if missing:
        failures.append(
            "SARIF driver metadata missing execution-context rules: "
            + ", ".join(sorted(missing))
        )

    report = {
        "budget_seconds": args.budget_seconds,
        "cold_seconds": round(cold_s, 3),
        "warm_seconds": round(warm_s, 3),
        "files_checked": files,
        "cold_cache": cold.get("cache"),
        "warm_cache": warm_cache,
        "failures": failures,
    }
    (art / "lint_budget.json").write_text(json.dumps(report, indent=2) + "\n")
    print(
        f"check_lint_budget: cold {cold_s:.1f}s, warm {warm_s:.1f}s "
        f"(budget {args.budget_seconds:.0f}s), warm cache "
        f"{warm_cache.get('hits')}/{files} hits"
    )
    for f in failures:
        print(f"check_lint_budget: FAIL: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
